"""PS service registration — one shard's RPC surface.

``PS.Lookup`` / ``PS.Update`` / ``PS.Pull`` / ``PS.Push`` / ``PS.Stats``
ride the normal dispatch path (auth, interceptors, limiters,
MethodStatus all apply).  With ``batch=True`` (the default) concurrent
Lookup and Update RPCs COALESCE through two DynamicBatchers — the first
non-autoregressive traffic shape the batcher has ever coalesced:

  * lookups queue as int64 key vectors, bucket-padded by KEY COUNT; one
    jitted [B, Lb] -> [B, Lb, D] gather serves the whole batch (one
    compile per bucket pair, the serving discipline);
  * updates queue as packed float64 rows (update_id + interleaved
    key/grad groups, length buckets 1 + k*(1+D)); one jitted scatter-add
    applies the whole batch, with idempotence decided per row at apply
    time under the shard lock.

Fault sites ``psserve.lookup`` / ``psserve.update`` cover the fan-out's
failure modes: ``stage="pre"`` fails a sub-call before any apply,
``stage="post"`` drops the ack AFTER the apply — the retried sub-call
must then dedup (chaos scenario 16 proves the version counter advances
exactly once).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from brpc_tpu import errors, fault
from brpc_tpu.rpc.service import Service, method
from brpc_tpu.psserve.shard import EmbeddingShardServer


class PSService(Service):
    NAME = "PS"

    def __init__(self, shard: EmbeddingShardServer,
                 lookup_batcher=None, update_batcher=None):
        self.shard = shard
        self._lookup_b = lookup_batcher
        self._update_b = update_batcher

    # ---- Lookup ----

    @method(request="json", response="json")
    def Lookup(self, cntl, req):
        keys = (req or {}).get("keys")
        if keys is None:
            cntl.set_failed(errors.EREQUEST, 'missing "keys"')
            return None
        if fault.ENABLED and fault.hit(
                "psserve.lookup", shard=self.shard.shard_index,
                n_keys=len(keys)) is not None:
            cntl.set_failed(errors.EINTERNAL,
                            "injected psserve.lookup fault")
            return None
        try:
            local = self.shard._to_local(np.asarray(keys, np.int64))
        except ValueError as e:
            cntl.set_failed(errors.EREQUEST, str(e))
            return None
        if self._lookup_b is None:
            try:
                rows, ver = self.shard.lookup(keys)  # counts + hot keys
            except ValueError as e:
                # e.g. a key-set larger than the biggest bucket: a
                # deterministic bad request, never an EINTERNAL crash
                cntl.set_failed(errors.EREQUEST, str(e))
                return None
            return {"rows": rows.tolist(), "version": ver}

        shard = self.shard

        def transform(row):
            # row: [n_keys, D] trimmed by the batcher's padded-output
            # scatter; version read at COMPLETION so any update acked
            # before this lookup's batch executed is covered.  Hot-key
            # and counter accounting happens HERE — only lookups that
            # were actually served shape the histogram (a shed/ELIMIT
            # reject never runs the transform), matching the unbatched
            # path
            shard._note_hot(local)
            with shard._mu:
                ver = shard.version
                shard.n_lookups += 1
            from brpc_tpu.psserve.shard import LOOKUPS, LOOKUP_KEYS
            LOOKUPS.add(1)
            LOOKUP_KEYS.add(int(row.shape[0]))
            return {"rows": np.asarray(row).tolist(), "version": ver}

        self._lookup_b.submit(cntl, local, transform=transform)
        return None     # deferred: the batch drainer completes the RPC

    # ---- Update ----

    @method(request="json", response="json")
    def Update(self, cntl, req):
        req = req or {}
        keys = req.get("keys")
        grads = req.get("grads")
        uid = req.get("update_id")
        if keys is None or grads is None:
            cntl.set_failed(errors.EREQUEST, 'missing "keys"/"grads"')
            return None
        if uid is not None:
            # the batched apply packs ids into float64 rows and uses 0
            # as the padding sentinel — an id outside (0, 2^53) would
            # be silently discarded (acked but never applied) or
            # rounded onto another id; refuse it loudly instead
            try:
                uid = int(uid)
            except (TypeError, ValueError):
                cntl.set_failed(errors.EREQUEST,
                                "update_id must be an integer")
                return None
            if not (0 < uid <= (1 << 53)):
                # inclusive upper bound: 2**53 itself is exactly
                # representable in float64 (it's 2**53 + 1 that isn't),
                # and PSClient's max mintable id lands exactly there
                # (salt/counter saturated at n_shards=32)
                cntl.set_failed(errors.EREQUEST,
                                "update_id must be in (0, 2**53]")
                return None
        if fault.ENABLED and fault.hit(
                "psserve.update", shard=self.shard.shard_index,
                stage="pre") is not None:
            # pre-apply failure: nothing was written; a retry applies
            # normally
            cntl.set_failed(errors.EINTERNAL,
                            "injected psserve.update fault (pre-apply)")
            return None
        try:
            local = self.shard._to_local(np.asarray(keys, np.int64))
            g = np.asarray(grads, np.float32)
            if g.shape != (local.shape[0], self.shard.dim):
                raise ValueError(f"grads shape {g.shape} != "
                                 f"({local.shape[0]}, {self.shard.dim})")
        except ValueError as e:
            cntl.set_failed(errors.EREQUEST, str(e))
            return None

        def ack(ver: int, dup: bool):
            if fault.ENABLED and fault.hit(
                    "psserve.update", shard=self.shard.shard_index,
                    stage="post") is not None:
                # post-apply ack drop: the update IS in the table; the
                # client's retry must be deduped by update_id or the
                # scatter-add doubles (chaos proves it doesn't)
                raise RuntimeError(
                    "injected psserve.update fault (post-apply)")
            return {"version": int(ver), "duplicate": bool(dup)}

        if self._update_b is None or uid is None:
            try:
                ver, dup = self.shard.update(keys, grads, update_id=uid)
            except ValueError as e:
                # oversize key-set etc.: deterministic bad request
                cntl.set_failed(errors.EREQUEST, str(e))
                return None
            try:
                return ack(ver, dup)
            except RuntimeError as e:
                cntl.set_failed(errors.EINTERNAL, str(e))
                return None
        row = EmbeddingShardServer.pack_update(int(uid), local, g)
        n_keys = int(local.shape[0])

        def transform(a):
            # a raising transform completes the RPC with EINTERNAL —
            # the post-apply ack-drop path above rides that contract.
            # UPDATE_KEYS counts here (the batch fn can't recover live
            # key counts from zero-padded rows), applied rows only
            if not bool(a[1]):
                from brpc_tpu.psserve.shard import UPDATE_KEYS
                UPDATE_KEYS.add(n_keys)
            return ack(int(a[0]), bool(a[1]))

        self._update_b.submit(cntl, row, transform=transform)
        return None

    # ---- dense params ----

    @method(request="json", response="json")
    def Pull(self, cntl, req):
        pname = (req or {}).get("name")
        if not pname:
            cntl.set_failed(errors.EREQUEST, 'missing "name"')
            return None
        try:
            v = self.shard.pull(pname)
        except KeyError:
            cntl.set_failed(errors.ENODATA, f"no dense param {pname!r}")
            return None
        return {"name": pname, "value": v.tolist(),
                "shape": list(v.shape)}

    @method(request="json", response="json")
    def Push(self, cntl, req):
        req = req or {}
        pname = req.get("name")
        delta = req.get("delta")
        if not pname or delta is None:
            cntl.set_failed(errors.EREQUEST, 'missing "name"/"delta"')
            return None
        try:
            ver, dup = self.shard.push(pname, delta,
                                       update_id=req.get("update_id"))
        except ValueError as e:
            cntl.set_failed(errors.EREQUEST, str(e))
            return None
        return {"version": int(ver), "duplicate": bool(dup)}

    @method(request="json", response="json")
    def Stats(self, cntl, req):
        return self.shard.stats()


def register_psserve(server, shard: EmbeddingShardServer, *,
                     batch: bool = True, max_batch_size: int = 16,
                     max_delay_us: int = 1000,
                     name: Optional[str] = None):
    """Expose one shard on an rpc Server; returns the PSService (its
    batchers close with ``unregister_psserve``)."""
    from brpc_tpu import psserve as _ps
    lookup_b = update_b = None
    safe = name or f"{shard.name}_{shard.shard_index}"
    if batch:
        from brpc_tpu.serving.batcher import DynamicBatcher
        lookup_b = DynamicBatcher(
            shard.lookup_batch_fn,
            max_batch_size=max_batch_size, max_delay_us=max_delay_us,
            length_buckets=shard.key_buckets,
            dtype=np.int64, padded_output=True,
            name=f"ps_lookup_{safe}")
        update_b = DynamicBatcher(
            shard.update_batch_fn,
            max_batch_size=max_batch_size, max_delay_us=max_delay_us,
            length_buckets=shard.update_length_buckets(),
            dtype=np.float64, padded_output=False,
            name=f"ps_update_{safe}")
    svc = PSService(shard, lookup_batcher=lookup_b,
                    update_batcher=update_b)
    server.add_service(svc)
    _ps._register_shard(shard, svc)
    return svc


def unregister_psserve(svc: PSService) -> None:
    """Close the service's batchers (flushes queued batches)."""
    for b in (svc._lookup_b, svc._update_b):
        if b is not None:
            b.close()
