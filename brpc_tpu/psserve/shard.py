"""EmbeddingShardServer — one partition of a sharded embedding table.

The parameter-server ownership map is contiguous row ranges
(:func:`shard_bounds`): shard i of n owns global rows ``[lo, hi)``.  A
shard answers

  * ``Lookup(keys) -> rows`` — gather of OWNED rows (the client routed
    the keys; duplicates are legal and each occurrence is served),
  * ``Update(keys, grads)`` — sparse scatter-add into the owned rows,
    idempotent by ``update_id`` so a retried sub-call (lost ack, chaos
    fault mid-fanout) can never double-apply,
  * ``Pull/Push(name)`` — dense whole-parameter read / delta-add for
    the rest of the model (owner chosen by name hash, client-side).

Every applied update advances the shard's VERSION counter, and every
lookup response carries the counter: an Update acked at version v is
visible to any Lookup issued afterwards (the batchers swap the table
reference before completing the RPC), which is the read-your-writes
contract the chaos suite leans on to prove exactly-once apply.

The gather/scatter hot paths are jitted once per key-count bucket
(requests pad up to ``key_buckets``), which is also the shape contract
the DynamicBatcher coalesces under (service.py).
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional, Sequence

import numpy as np

from brpc_tpu.bvar import Adder
from brpc_tpu.butil.lockprof import InstrumentedLock

DEFAULT_KEY_BUCKETS = (8, 32, 128, 512)

# process-wide counters (per-shard numbers live on the instance and the
# /psserve page; these feed /brpc_metrics as psserve_*)
LOOKUPS = Adder("psserve_lookups")
LOOKUP_KEYS = Adder("psserve_lookup_keys")
UPDATES = Adder("psserve_updates")
UPDATE_KEYS = Adder("psserve_update_keys")
DUP_UPDATES = Adder("psserve_dup_updates")
OPT_UPDATES = Adder("psserve_opt_updates")
PULLS = Adder("psserve_pulls")
PUSHES = Adder("psserve_pushes")


def shard_bounds(vocab: int, n_shards: int) -> list[tuple[int, int]]:
    """Contiguous ownership ranges: shard i owns rows [lo, hi).  The
    remainder spreads over the FIRST shards so every shard's size
    differs by at most one row."""
    if n_shards < 1 or vocab < n_shards:
        raise ValueError(f"need 1 <= n_shards <= vocab, got "
                         f"{n_shards}/{vocab}")
    base, rem = divmod(vocab, n_shards)
    bounds = []
    lo = 0
    for i in range(n_shards):
        hi = lo + base + (1 if i < rem else 0)
        bounds.append((lo, hi))
        lo = hi
    return bounds


def owners_for(keys: np.ndarray, bounds: Sequence[tuple[int, int]]
               ) -> np.ndarray:
    """Owning shard index per key (vectorized over the range table)."""
    los = np.asarray([b[0] for b in bounds])
    return (np.searchsorted(los, np.asarray(keys), side="right") - 1
            ).astype(np.int64)


def init_embedding_table(vocab: int, dim: int, seed: int = 0) -> np.ndarray:
    """The deterministic full table every shard slices its rows from —
    also the test oracle's starting point."""
    rng = np.random.default_rng(seed)
    return (rng.standard_normal((vocab, dim)) * 0.02).astype(np.float32)


def _bucket_up(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"{n} keys exceed largest bucket {buckets[-1]}")


class EmbeddingShardServer:
    """One partition's state + the jitted gather/scatter hot paths."""

    def __init__(self, shard_index: int, n_shards: int, vocab: int,
                 dim: int, *, seed: int = 0,
                 table: Optional[np.ndarray] = None,
                 dense_params: Optional[dict] = None,
                 mesh=None,
                 key_buckets: Sequence[int] = DEFAULT_KEY_BUCKETS,
                 applied_cap: int = 65536,
                 name: str = "ps"):
        import jax
        import jax.numpy as jnp
        self._jax, self._jnp = jax, jnp
        self.shard_index = int(shard_index)
        self.n_shards = int(n_shards)
        self.vocab = int(vocab)
        self.dim = int(dim)
        self.name = name
        self.key_buckets = tuple(sorted(key_buckets))
        self.bounds = shard_bounds(vocab, n_shards)
        self.lo, self.hi = self.bounds[self.shard_index]
        full = table if table is not None else \
            init_embedding_table(vocab, dim, seed)
        rows = np.asarray(full[self.lo:self.hi], dtype=np.float32)
        if mesh is not None:
            # row-shard THIS partition's rows over the tp ICI mesh (the
            # PR 10 NamedSharding machinery): a co-located pod splits
            # each partition again across its chips
            from jax.sharding import NamedSharding, PartitionSpec as P
            tp = mesh.shape.get("tp", 1)
            if rows.shape[0] % tp == 0:
                self._rows = jax.device_put(
                    rows, NamedSharding(mesh, P("tp", None)))
            else:   # uneven rows: keep replicated rather than refuse
                self._rows = jax.device_put(
                    rows, NamedSharding(mesh, P()))
        else:
            self._rows = jnp.asarray(rows)
        self.mesh = mesh
        # dense parameters (the non-embedding rest of the model); the
        # CLIENT routes each name to its owner shard by hash
        self._dense: dict[str, np.ndarray] = {
            k: np.asarray(v, np.float32)
            for k, v in (dense_params or {}).items()}
        self._mu = InstrumentedLock("psserve.shard_apply",
                                    threading.RLock())
        self.version = 0
        self._applied: OrderedDict[int, int] = OrderedDict()  # uid -> ver
        self._applied_cap = int(applied_cap)
        # co-located optimizer slots (ISSUE 17): per-row momentum /
        # Adam m/v/step tables, lazily allocated on the first
        # optimizer-carrying update, living WITH the rows (same
        # sharding) so they never cross the wire
        self._slots: dict = {}
        # per-shard counters (process-wide Adders above aggregate)
        self.n_lookups = 0
        self.n_updates = 0
        self.n_opt_updates = 0
        self.n_dup_updates = 0
        self.n_pulls = 0
        self.n_pushes = 0
        # hot-key histogram (bounded: prune to the top half at 4096)
        self._hot: dict[int, int] = {}

        # one jit each; bucket padding bounds the compile count
        self._gather = jax.jit(lambda t, k: t[k])
        self._scatter = jax.jit(lambda t, k, g: t.at[k].add(g))
        # CPU fast path (ISSUE 13): with no device mesh, a bucketed
        # gather is a plain numpy fancy-index over a zero-copy view of
        # the jax array — bit-identical to the jitted gather, without
        # ~200us of dispatch per call.  On a real mesh the jit path
        # stays (the gather must run where the rows live).
        #
        # Lock discipline (ISSUE 17): the fused optimizer apply DONATES
        # rows and slots, overwriting the old buffers in place, so the
        # swap-on-update immutability the zero-copy view used to rely
        # on no longer holds.  Every raw read of ``self._rows`` /
        # ``self._slots`` must COMPLETE under ``self._mu`` (the gather
        # result is a fresh array, so nothing aliasing the table
        # escapes the lock); snapshots hand out copies.
        self._cpu_fast = mesh is None and jax.default_backend() == "cpu"

    # ---- ownership helpers ----

    @property
    def n_rows(self) -> int:
        return self.hi - self.lo

    def owns(self, keys: np.ndarray) -> np.ndarray:
        keys = np.asarray(keys)
        return (keys >= self.lo) & (keys < self.hi)

    def _to_local(self, keys) -> np.ndarray:
        keys = np.asarray(keys, dtype=np.int64)
        if keys.size and (keys.min() < self.lo or keys.max() >= self.hi):
            raise ValueError(
                f"shard {self.shard_index} owns [{self.lo},{self.hi}), "
                f"got keys outside the range")
        return keys - self.lo

    def _note_hot(self, local_keys: np.ndarray) -> None:
        uniq, counts = np.unique(local_keys, return_counts=True)
        with self._mu:      # RLock: callers inside the lock re-enter
            hot = self._hot
            for k, c in zip(uniq.tolist(), counts.tolist()):
                hot[k + self.lo] = hot.get(k + self.lo, 0) + c
            if len(hot) > 4096:
                keep = sorted(hot.items(), key=lambda kv: -kv[1])[:2048]
                self._hot = dict(keep)

    # ---- direct (unbatched) entry points ----

    def lookup(self, keys) -> tuple[np.ndarray, int]:
        """Gather owned rows for GLOBAL keys; returns (rows [n, dim],
        shard version at serve time)."""
        local = self._to_local(keys)
        n = local.shape[0]
        b = _bucket_up(max(n, 1), self.key_buckets)
        with self._mu:
            # the gather must FINISH under the lock: the fused
            # optimizer apply donates the table buffer and overwrites
            # it in place (see the lock-discipline note in __init__) —
            # the fancy-index / forced gather below returns a copy, so
            # nothing aliasing the table leaves the critical section
            if self._cpu_fast:
                rows = np.asarray(self._rows)[local]
            else:
                padded = np.zeros((b,), np.int64)
                padded[:n] = local
                rows = np.asarray(self._gather(self._rows, padded))[:n]
            ver = self.version
            self.n_lookups += 1
            self._note_hot(local)
        LOOKUPS.add(1)
        LOOKUP_KEYS.add(int(n))
        return rows, ver

    def update(self, keys, grads, update_id: Optional[int] = None
               ) -> tuple[int, bool]:
        """Sparse scatter-add for GLOBAL keys; returns (version after
        the apply, was_duplicate).  A duplicate ``update_id`` acks with
        the ORIGINAL apply's version and touches nothing."""
        local = self._to_local(keys)
        grads = np.asarray(grads, np.float32)
        if grads.shape != (local.shape[0], self.dim):
            raise ValueError(f"grads shape {grads.shape} != "
                             f"({local.shape[0]}, {self.dim})")
        with self._mu:
            if update_id is not None and update_id in self._applied:
                self.n_dup_updates += 1
                DUP_UPDATES.add(1)
                return self._applied[update_id], True
            self._apply_locked(local, grads)
            ver = self.version
            if update_id is not None:
                self._record_applied_locked(update_id, ver)
            self.n_updates += 1
        UPDATES.add(1)
        UPDATE_KEYS.add(int(local.shape[0]))
        return ver, False

    def _apply_locked(self, local: np.ndarray, grads: np.ndarray) -> None:
        n = local.shape[0]
        b = _bucket_up(max(n, 1), self.key_buckets)
        pk = np.zeros((b,), np.int64)
        pg = np.zeros((b, self.dim), np.float32)
        pk[:n] = local
        pg[:n] = grads          # padded rows add 0 to row 0: a no-op
        self._rows = self._scatter(self._rows, pk, pg)
        self.version += 1

    # ---- the fused co-located optimizer apply (ISSUE 17) ----

    def _ensure_slots_locked(self, spec) -> None:
        jnp = self._jnp
        if "m" not in self._slots:
            # zeros_like preserves the rows' sharding: on a tp mesh
            # the momentum rows live exactly where their table rows do
            self._slots["m"] = jnp.zeros_like(self._rows)
        if spec.kind == "adam":
            if "v" not in self._slots:
                self._slots["v"] = jnp.zeros_like(self._rows)
            if "t" not in self._slots:
                self._slots["t"] = jnp.zeros((self.n_rows,), jnp.float32)

    def update_opt(self, keys, grads, spec,
                   update_id: Optional[int] = None) -> tuple[int, bool]:
        """``update`` with co-located optimizer state: the gradient
        scatter AND the slot step run as ONE jitted program per key
        bucket (train/optimizer.py), under the same lock, version
        counter and applied-id dedup as the plain scatter-add — so a
        retried wave acks the ORIGINAL version and can never
        double-step momentum.  The client sends RAW gradients; the
        slot rows never cross the wire."""
        from brpc_tpu.train.optimizer import fused_apply
        local = self._to_local(keys)
        grads = np.asarray(grads, np.float32)
        if grads.shape != (local.shape[0], self.dim):
            raise ValueError(f"grads shape {grads.shape} != "
                             f"({local.shape[0]}, {self.dim})")
        fn = fused_apply(spec.kind)
        n = local.shape[0]
        b = _bucket_up(max(n, 1), self.key_buckets)
        pk = np.zeros((b,), np.int64)
        pg = np.zeros((b, self.dim), np.float32)
        # padding entries carry valid=0: they add no gradient AND do
        # not mark row 0 touched (a plain zero-grad pad would still
        # decay row 0's momentum — the mask is what makes padding a
        # true no-op under an optimizer)
        pv = np.zeros((b,), np.float32)
        pk[:n] = local
        pg[:n] = grads
        pv[:n] = 1.0
        with self._mu:
            if update_id is not None and update_id in self._applied:
                self.n_dup_updates += 1
                DUP_UPDATES.add(1)
                return self._applied[update_id], True
            self._ensure_slots_locked(spec)
            s = self._slots
            if spec.kind == "sgdm":
                self._rows, s["m"] = fn(
                    self._rows, s["m"], pk, pg, pv,
                    spec.lr, spec.momentum)
            else:
                self._rows, s["m"], s["v"], s["t"] = fn(
                    self._rows, s["m"], s["v"], s["t"], pk, pg, pv,
                    spec.lr, spec.beta1, spec.beta2, spec.eps)
            self.version += 1
            ver = self.version
            if update_id is not None:
                self._record_applied_locked(update_id, ver)
            self.n_updates += 1
            self.n_opt_updates += 1
            # no _note_hot here: key heat feeds migration's hot-shard
            # detection and means READ traffic — lookups track it, the
            # plain update path doesn't, and a trainer hammering its
            # own rows every wave must not masquerade as serving heat
            # (it is also ~1ms of python dict loop per wave)
        UPDATES.add(1)
        OPT_UPDATES.add(1)
        UPDATE_KEYS.add(int(n))
        return ver, False

    def snapshot_slots(self) -> dict:
        """Current optimizer slot tables as numpy (tests compare
        against the dense oracle's slots)."""
        with self._mu:
            # np.array (not asarray): the caller keeps the snapshot
            # past the lock, and the next donated apply overwrites the
            # buffer a zero-copy view would still be pointing at
            return {k: np.array(v) for k, v in self._slots.items()}

    def _record_applied_locked(self, uid: int, ver: int) -> None:
        self._applied[uid] = ver
        while len(self._applied) > self._applied_cap:
            self._applied.popitem(last=False)

    # ---- dense Pull/Push ----

    def pull(self, pname: str) -> np.ndarray:
        with self._mu:
            if pname not in self._dense:
                raise KeyError(pname)
            self.n_pulls += 1
            out = self._dense[pname].copy()
        PULLS.add(1)
        return out

    def push(self, pname: str, delta, update_id: Optional[int] = None,
             ) -> tuple[int, bool]:
        delta = np.asarray(delta, np.float32)
        with self._mu:
            if update_id is not None and update_id in self._applied:
                self.n_dup_updates += 1
                DUP_UPDATES.add(1)
                return self._applied[update_id], True
            cur = self._dense.get(pname)
            if cur is None:
                self._dense[pname] = delta.copy()
            else:
                if cur.shape != delta.shape:
                    raise ValueError(f"push {pname}: shape {delta.shape} "
                                     f"!= {cur.shape}")
                self._dense[pname] = cur + delta
            self.version += 1
            ver = self.version
            if update_id is not None:
                self._record_applied_locked(update_id, ver)
            self.n_pushes += 1
        PUSHES.add(1)
        return ver, False

    # ---- DynamicBatcher batch_fns (service.py wires these) ----
    #
    # Lookup rows are int64 key vectors; the batch gather is ONE jitted
    # [B, Lb] -> [B, Lb, D] op per bucket pair (padded key 0 gathers
    # row 0 and is trimmed away by the batcher's padded-output scatter).

    def lookup_batch_fn(self, padded: np.ndarray) -> np.ndarray:
        # per-request accounting (live-row counts, hot keys) happens in
        # the service handler — this fn sees bucket-padded rows and
        # cannot tell live from padding
        k = np.asarray(padded, np.int64)
        with self._mu:
            # complete the gather under the lock — the fused optimizer
            # apply donates and overwrites the table in place, so the
            # zero-copy view must not be read outside the critical
            # section (the fancy-index result is a fresh array)
            if self._cpu_fast:
                return np.asarray(self._rows)[k]
            return np.asarray(self._gather(self._rows, k))

    # Update rows pack (update_id, then per key [key, grad...]) into ONE
    # float64 vector: [uid, k0, g0_0..g0_{D-1}, k1, g1_0..].  float64
    # carries 53-bit update ids and float32 grads exactly; the length
    # buckets are 1 + k*(1+D) so the padded batch reshapes to
    # [B, kb, 1+D] (zero rows scatter grad 0 into row 0: a no-op).
    # Dedup is decided here, at APPLY time under the shard lock — the
    # only point where "already applied" is unambiguous.

    def update_length_buckets(self) -> tuple:
        return tuple(1 + k * (1 + self.dim) for k in self.key_buckets)

    @staticmethod
    def pack_update(update_id: int, local_keys: np.ndarray,
                    grads: np.ndarray) -> np.ndarray:
        n, d = grads.shape
        row = np.empty((1 + n * (1 + d),), np.float64)
        row[0] = float(update_id)
        body = row[1:].reshape(n, 1 + d)
        body[:, 0] = local_keys
        body[:, 1:] = grads
        return row

    def update_batch_fn(self, padded: np.ndarray) -> np.ndarray:
        """One coalesced scatter-add for every update row in the batch;
        returns per-row [version, dup_flag] acks."""
        B, Lb = padded.shape
        kb = (Lb - 1) // (1 + self.dim)
        body = np.ascontiguousarray(
            padded[:, 1:1 + kb * (1 + self.dim)]
        ).reshape(B, kb, 1 + self.dim)
        keys = body[:, :, 0].astype(np.int64)
        grads = body[:, :, 1:].astype(np.float32)
        uids = padded[:, 0].astype(np.int64)
        return self._apply_update_batch(uids, keys, grads)

    # The BINARY update path (tensorframe wire, ISSUE 13) packs bytes,
    # not float64: one record is [update_id u64][key i64, grad f32*D] x k
    # — vectorized byte views in and out, no per-element float64
    # conversion and no 53-bit packing ceiling on the row format.
    # Padding bytes are zero = key 0 grad 0 groups, a scatter no-op,
    # exactly the float64 scheme's discipline; both paths share
    # _apply_update_batch, so dedup is decided against ONE applied set
    # no matter which wire a retry arrives on.

    def update_record_buckets(self) -> tuple:
        return tuple(8 + k * (8 + 4 * self.dim) for k in self.key_buckets)

    @staticmethod
    def pack_update_record(update_id: int, local_keys: np.ndarray,
                           grads: np.ndarray) -> np.ndarray:
        """One uint8 record from int64 keys + float32 grads (views in:
        the frame's decoded tensors splice by vectorized byte copy)."""
        import struct as _struct
        n, d = grads.shape
        rec = np.empty((8 + n * (8 + 4 * d),), np.uint8)
        rec[:8] = np.frombuffer(_struct.pack("<Q", update_id), np.uint8)
        body = rec[8:].reshape(n, 8 + 4 * d)
        body[:, :8] = np.ascontiguousarray(
            local_keys, "<i8").view(np.uint8).reshape(n, 8)
        body[:, 8:] = np.ascontiguousarray(
            grads, "<f4").view(np.uint8).reshape(n, 4 * d)
        return rec

    def update_batch_fn_binary(self, padded: np.ndarray) -> np.ndarray:
        """update_batch_fn for uint8 records: reinterpret the byte
        columns as (uids, keys, grads) with three vectorized copies,
        then the shared apply."""
        B, Lb = padded.shape
        kb = (Lb - 8) // (8 + 4 * self.dim)
        uids = np.ascontiguousarray(
            padded[:, :8]).view("<u8").reshape(B).astype(np.int64)
        body = np.ascontiguousarray(
            padded[:, 8:8 + kb * (8 + 4 * self.dim)]
        ).reshape(B, kb, 8 + 4 * self.dim)
        keys = np.ascontiguousarray(
            body[:, :, :8]).view("<i8").reshape(B, kb)
        grads = np.ascontiguousarray(
            body[:, :, 8:]).view("<f4").reshape(B, kb, self.dim)
        return self._apply_update_batch(uids, keys, grads)

    def _apply_update_batch(self, uids: np.ndarray, keys: np.ndarray,
                            grads: np.ndarray) -> np.ndarray:
        """The ONE coalesced apply both wire formats feed: per-row
        dedup (applied set + intra-batch), one compiled scatter, acks
        [version, dup_flag] per row.  uid 0 marks batch padding."""
        B = keys.shape[0]
        acks = np.zeros((B, 2), np.float64)
        with self._mu:
            # dedup against the applied set AND within this batch: a
            # retry can land in the SAME batch as its original (reply
            # lost before the batch formed) — both rows would pass the
            # applied-set check, and double-applying here is exactly
            # the violation update_ids exist to prevent
            first_row: dict[int, int] = {}
            batch_dups: list[tuple[int, int]] = []   # (row, first row)
            for i in range(B):
                uid = int(uids[i])
                if uid == 0:
                    continue            # batch padding, not a request
                if uid in self._applied:
                    self.n_dup_updates += 1
                    DUP_UPDATES.add(1)
                    acks[i] = (self._applied[uid], 1.0)
                    # zero the row out of the scatter: served from the
                    # applied set, never re-added
                    keys[i] = 0
                    grads[i] = 0.0
                    continue
                if uid in first_row:
                    batch_dups.append((i, first_row[uid]))
                    keys[i] = 0
                    grads[i] = 0.0
                    continue
                first_row[uid] = i
            # ONE compiled scatter for the whole batch (compile per
            # (batch bucket, key bucket) pair); dup/padding rows are
            # zeroed above so they contribute nothing
            self._rows = self._scatter(
                self._rows, keys.reshape(-1),
                grads.reshape(-1, self.dim))
            for uid, i in first_row.items():
                self.version += 1
                self._record_applied_locked(uid, self.version)
                acks[i] = (self.version, 0.0)
                self.n_updates += 1
                UPDATES.add(1)
            for i, j in batch_dups:
                # ack the retry with the ORIGINAL apply's version
                self.n_dup_updates += 1
                DUP_UPDATES.add(1)
                acks[i] = (acks[j, 0], 1.0)
        return acks

    # ---- introspection (/psserve) ----

    def hot_keys(self, top: int = 10) -> list[tuple[int, int]]:
        with self._mu:
            return sorted(self._hot.items(), key=lambda kv: -kv[1])[:top]

    def stats(self) -> dict:
        with self._mu:
            return {
                "name": self.name,
                "shard_index": self.shard_index,
                "n_shards": self.n_shards,
                "rows": self.n_rows,
                "range": [self.lo, self.hi],
                "dim": self.dim,
                "version": self.version,
                "lookups": self.n_lookups,
                "updates": self.n_updates,
                "opt_updates": self.n_opt_updates,
                "opt_slots": sorted(self._slots),
                "dup_updates": self.n_dup_updates,
                "pulls": self.n_pulls,
                "pushes": self.n_pushes,
                "dense_params": sorted(self._dense),
                "applied_ids": len(self._applied),
                "hot_keys": self.hot_keys(),
                "mesh": (dict(self.mesh.shape) if self.mesh is not None
                         else None),
            }

    def snapshot_rows(self) -> np.ndarray:
        """The shard's current rows as numpy (tests compare against the
        dense oracle)."""
        with self._mu:
            # copy, not view: the donated optimizer apply overwrites
            # the table buffer in place after the lock is released
            return np.array(self._rows)
