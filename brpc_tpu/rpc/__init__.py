from brpc_tpu.rpc.channel import (  # noqa: F401
    Channel, ChannelOptions, RetryPolicy, SocketMap, CallManager,
)
from brpc_tpu.rpc.controller import Controller  # noqa: F401
from brpc_tpu.rpc.server import Server, ServerOptions, MethodStatus  # noqa: F401
from brpc_tpu.rpc.service import Service, method  # noqa: F401
from brpc_tpu.rpc.stream import (  # noqa: F401
    Stream, StreamHandler, stream_create, stream_accept,
)
from brpc_tpu.rpc.combo_channels import (  # noqa: F401
    CallMapper, DynamicPartitionChannel, ParallelChannel, PartitionChannel,
    PartitionParser, ResponseMerger, SelectiveChannel, SubCall, SumMerger,
)
from brpc_tpu.rpc.auth import (  # noqa: F401
    Authenticator, HmacAuthenticator, TokenAuthenticator,
)
from brpc_tpu.rpc.memcache import (  # noqa: F401
    MemcacheChannel, MemcacheError, MemcacheService, MemoryMemcacheService,
)
from brpc_tpu.rpc.thrift import (  # noqa: F401
    TField, ThriftChannel, ThriftError, ThriftService,
)
from brpc_tpu.rpc.mongo import (  # noqa: F401
    MongoClient, MongoService,
)
from brpc_tpu.rpc.h2 import GrpcChannel  # noqa: F401
from brpc_tpu.rpc.data_pool import (  # noqa: F401
    DataFactory, SimpleDataPool,
)
from brpc_tpu.rpc.progressive import (  # noqa: F401
    ProgressiveAttachment, ProgressiveResponse,
)
from brpc_tpu.rpc.http import (  # noqa: F401
    HttpChannel, HttpResponse, HttpStreamReader,
)
from brpc_tpu.rpc.redis import (  # noqa: F401
    MemoryRedisService, RedisChannel, RedisError, RedisPipeline,
    RedisService,
)
from brpc_tpu.rpc import meta  # noqa: F401
