"""Authentication — credential generation/verification on the first (here:
every) message of a connection.

Reference: authenticator.h (Authenticator::GenerateCredential/
VerifyCredential; per-protocol first-message piggyback, SURVEY.md §2.5).
Our native frame meta carries the credential on every request (meta.auth),
so verification is per-request rather than per-connection — strictly
stronger, and it survives connection pooling/multiplexing.

Plug into ChannelOptions.auth (client: generate) and ServerOptions.auth
(server: verify).  gRPC traffic carries the credential in the standard
``authorization`` metadata header (server.invoke_grpc).
"""
from __future__ import annotations

import hashlib
import hmac
import os
import threading
import time
from typing import Optional


class Authenticator:
    """Duck-typed interface used by Channel/Server."""

    def generate_credential(self) -> bytes:
        raise NotImplementedError

    def verify_credential(self, credential: bytes) -> bool:
        raise NotImplementedError


class TokenAuthenticator(Authenticator):
    """Shared static token (the simplest useful policy)."""

    def __init__(self, token: str | bytes):
        self._token = token.encode() if isinstance(token, str) else token

    def generate_credential(self) -> bytes:
        return self._token

    def verify_credential(self, credential: bytes) -> bool:
        if isinstance(credential, str):
            credential = credential.encode()
        return hmac.compare_digest(credential or b"", self._token)


class HmacAuthenticator(Authenticator):
    """Replay-resistant HMAC over a timestamp+nonce: credential =
    ``ts.nonce.hex(HMAC_SHA256(key, ts.nonce))``.  Verification enforces a
    clock-skew window AND rejects nonces already seen inside it, so a
    captured credential cannot be replayed (seen-nonce set is pruned as
    timestamps age out; memory is bounded by the genuine request rate).

    NOTE: a client must generate a FRESH credential per connection/request
    (ChannelOptions.auth does — generate_credential is called per call).
    Reusing one credential object across calls would self-trip the replay
    check."""

    def __init__(self, key: str | bytes, max_skew_s: float = 300.0,
                 track_nonces: bool = True):
        self._key = key.encode() if isinstance(key, str) else key
        self._max_skew_s = max_skew_s
        self._track = track_nonces
        self._seen: dict[bytes, float] = {}   # nonce -> expiry
        # expiry-ordered FIFO alongside the dict: nonces are appended with
        # monotonically increasing expiries, so pruning pops from the left
        # until the head is unexpired — amortized O(1) per verify, never a
        # full-dict rebuild on the hot path
        from collections import deque
        self._seen_order: "deque[tuple[float, bytes]]" = deque()
        self._seen_lock = threading.Lock()

    def _sign(self, ts: bytes, nonce: bytes) -> str:
        return hmac.new(self._key, ts + b"." + nonce,
                        hashlib.sha256).hexdigest()

    def generate_credential(self) -> bytes:
        ts = str(int(time.time())).encode()
        nonce = os.urandom(8).hex().encode()
        return ts + b"." + nonce + b"." + self._sign(ts, nonce).encode()

    def verify_credential(self, credential: bytes) -> bool:
        if isinstance(credential, str):
            credential = credential.encode()
        try:
            ts, nonce, mac = credential.split(b".", 2)
            now = time.time()
            if abs(now - int(ts)) > self._max_skew_s:
                return False
            if not hmac.compare_digest(mac.decode(), self._sign(ts, nonce)):
                return False
            if self._track:
                with self._seen_lock:
                    while self._seen_order and self._seen_order[0][0] <= now:
                        _, old = self._seen_order.popleft()
                        if self._seen.get(old, 0) <= now:
                            self._seen.pop(old, None)
                    exp = self._seen.get(nonce)
                    if exp is not None and exp > now:
                        return False  # replay inside the window
                    expiry = now + self._max_skew_s
                    self._seen[nonce] = expiry
                    self._seen_order.append((expiry, nonce))
            return True
        except (ValueError, UnicodeDecodeError):
            return False
