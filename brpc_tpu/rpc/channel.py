"""Channel — the client endpoint (reference channel.{h,cpp}; SURVEY.md §2.5).

Keeps the reference's client machinery shapes:
  * Channel.init("host:port" | "proto://cluster", lb) — naming service +
    load balancer resolve per call (channel.h:161).
  * CallMethod drives a per-call state machine on the Controller:
    (correlation_id, attempt) versioning so stale attempts can't complete a
    call twice (the bthread_id range trick, controller.h:692-703), retries
    re-issued on a different server with failed ones excluded
    (excluded_servers.h), backup requests racing a second attempt after
    backup_request_ms (channel.cpp:403-409), one overall deadline timer.
  * SocketMap: endpoint -> native socket reuse (socket_map.h:147).
"""
from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from brpc_tpu import errors
from brpc_tpu.butil.endpoint import EndPoint, str2endpoint
from brpc_tpu.rpc import meta as M
from brpc_tpu.rpc.controller import Controller, OneShotEvent
from brpc_tpu.rpc.serialization import compress, decompress, get_serializer
from brpc_tpu.rpc.transport import MSG_TRPC, Transport

_cid_counter = itertools.count(1)


@dataclass
class ChannelOptions:
    timeout_ms: int = 500                  # same default as ChannelOptions
    max_retry: int = 3
    backup_request_ms: int = -1            # <0 disables
    connection_type: str = "single"        # single | pooled | short
    protocol: str = "trpc"
    compress_type: int = M.COMPRESS_NONE
    load_balancer: str = ""                # "" = single server
    auth: Optional[Any] = None             # Authenticator
    retry_policy: Optional[Any] = None
    # availability floor for circuit breaking (ClusterRecoverPolicy);
    # None = isolate freely (single-server channels have no cluster)
    cluster_recover_policy: Optional[Any] = None
    # In-socket TLS (rpc/tls_engine.py): an ssl.SSLContext for client-side
    # TLS to this channel's servers.  Registered per endpoint on the
    # shared SocketMap (mirrors the reference's per-Channel
    # ChannelSSLOptions, socket.h SSL integration).
    tls_context: Optional[Any] = None
    tls_server_hostname: Optional[str] = None


class RetryPolicy:
    """DoRetry(cntl) — reference retry_policy.h semantics: retry connection
    errors, not deadline misses."""

    RETRYABLE = {errors.EFAILEDSOCKET, errors.EOVERCROWDED, errors.EEOF,
                 errors.ECONNREFUSED, errors.EINTERNAL}

    def do_retry(self, cntl: Controller) -> bool:
        return cntl.error_code in self.RETRYABLE


DEFAULT_RETRY_POLICY = RetryPolicy()


class _ClientConn:
    __slots__ = ("sid", "endpoint", "tls")

    def __init__(self, sid: int, endpoint: EndPoint):
        self.sid = sid
        self.endpoint = endpoint
        self.tls = False   # set by SocketMap._connect when TLS-wrapped


class SocketMap:
    """endpoint -> client connections (reference socket_map.h:147 +
    ConnectionType, protocol.h:161-180).  Three reuse schemes:

      * single — one shared multiplexed connection per endpoint (our TRPC
        framing correlates by id, so one socket carries any number of
        in-flight calls; the reference default for baidu_std).
      * pooled — a free-list of connections per endpoint; a call checks one
        out for its attempt and returns it at completion (the reference
        scheme for non-multiplexable protocols; here it also isolates large
        transfers from head-of-line blocking on the shared socket).
      * short  — a fresh connection per attempt, closed at call end.

    All client connections share one response handler (CallManager)."""

    _instance = None
    _instance_lock = threading.Lock()

    @classmethod
    def instance(cls) -> "SocketMap":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def __init__(self):
        self._lock = threading.Lock()
        # ep -> (ssl_context, server_hostname): connections to these
        # endpoints are TLS-wrapped at connect time (in-socket TLS)
        self._tls_eps: dict[EndPoint, tuple] = {}
        self._conns: dict[EndPoint, _ClientConn] = {}
        self._sid_to_ep: dict[int, EndPoint] = {}
        self._pool: dict[EndPoint, list[_ClientConn]] = {}
        self._pooled_sids: dict[int, _ClientConn] = {}
        self._closing: set[int] = set()   # deliberate local closes

    def _connect(self, ep: EndPoint) -> _ClientConn:
        mgr = CallManager.instance()
        # unix-scheme endpoints carry the path in .host; the native layer
        # selects AF_UNIX on the "unix:" prefix (butil/unix_socket role)
        host = f"unix:{ep.host}" if ep.scheme == "unix" else ep.host
        sid = Transport.instance().connect_rpc(
            host, ep.port, mgr.on_message, self._on_socket_failed,
            on_response=mgr.on_fast_response)
        tls = self._tls_eps.get(ep)
        if tls is not None:
            # wrap BEFORE returning: no caller may write plaintext first
            Transport.instance().enable_tls(
                sid, tls[0], server_side=False, server_hostname=tls[1])
        with self._lock:
            self._sid_to_ep[sid] = ep
        conn = _ClientConn(sid, ep)
        conn.tls = tls is not None
        return conn

    def set_endpoint_tls(self, ep, context, server_hostname=None) -> None:
        with self._lock:
            self._tls_eps[ep] = (context, server_hostname)

    def get_connection(self, ep: EndPoint) -> _ClientConn:
        with self._lock:
            c = self._conns.get(ep)
            want_tls = ep in self._tls_eps
            if c is not None and getattr(c, "tls", False) != want_tls:
                # TLS was registered for this endpoint AFTER a plaintext
                # connection was cached (or vice versa): reusing it would
                # send bytes in the wrong cryptographic mode — drop it
                # and reconnect in the registered mode
                self._conns.pop(ep, None)
                stale, c = c, None
            else:
                stale = None
            if c is not None:
                return c
        if stale is not None:
            self.close_quietly(stale.sid)
        c = self._connect(ep)
        with self._lock:
            cur = self._conns.get(ep)
            if cur is None:
                self._conns[ep] = c
        # NOTE: never close (or do anything that can fire socket callbacks)
        # while holding _lock — the native SetFailed invokes on_failed
        # synchronously on this thread, and _on_socket_failed re-takes _lock.
        if cur is not None:
            # lost the race; keep the established one, drop ours
            self.close_quietly(c.sid)
            return cur
        return c

    # ---- pooled scheme ----

    def get_pooled(self, ep: EndPoint) -> _ClientConn:
        t = Transport.instance()
        while True:
            with self._lock:
                free = self._pool.get(ep)
                c = free.pop() if free else None
            if c is None:
                return self._connect(ep)
            if t.alive(c.sid):
                return c
            # died while idle in the pool; try the next one

    def return_pooled(self, c: _ClientConn) -> None:
        if not Transport.instance().alive(c.sid):
            return
        with self._lock:
            self._pooled_sids[c.sid] = c
            self._pool.setdefault(c.endpoint, []).append(c)

    # ---- short scheme ----

    def make_short(self, ep: EndPoint) -> _ClientConn:
        return self._connect(ep)

    def close_quietly(self, sid: int) -> None:
        """Deliberate local close — not a server failure: skips the
        health-check / circuit-breaker marking that real failures get."""
        with self._lock:
            self._closing.add(sid)
        Transport.instance().close(sid)

    def _on_socket_failed(self, sid: int, err: int) -> None:
        with self._lock:
            deliberate = sid in self._closing
            self._closing.discard(sid)
            ep = self._sid_to_ep.pop(sid, None)
            if ep is not None and self._conns.get(ep) is not None and \
                    self._conns[ep].sid == sid:
                del self._conns[ep]
            pc = self._pooled_sids.pop(sid, None)
            if pc is not None and ep is not None:
                free = self._pool.get(ep)
                if free and pc in free:
                    free.remove(pc)
        CallManager.instance().on_socket_failed(sid, err)
        # streams riding the dead connection are unrecoverable: close
        # them so their handlers learn now (ISSUE 8 — the router's
        # replica failover keys off on_closed, and a silently-dead
        # peer sends no CLOSE frame)
        from brpc_tpu.rpc.stream import StreamRegistry
        StreamRegistry.instance().on_socket_failed(sid)
        # health check + LB notification (policy layer)
        from brpc_tpu.policy.health_check import on_connection_failed
        if ep is not None and not deliberate:
            on_connection_failed(ep)

    def evict(self, ep: EndPoint, sid: int) -> None:
        """Drop the cached single-connection mapping for `ep` iff it
        still points at `sid` — no close, no failure marking.  Used when
        a write already failed on `sid`: the socket is dying, but its
        failed-callback cleanup may still be in flight on another
        thread, and a retry that re-checks out the same dying
        connection burns every attempt on it (found by chaos injection,
        tests/test_chaos.py mid-call reset)."""
        with self._lock:
            c = self._conns.get(ep)
            if c is not None and c.sid == sid:
                del self._conns[ep]

    def drop(self, ep: EndPoint) -> None:
        with self._lock:
            c = self._conns.pop(ep, None)
            free = self._pool.pop(ep, [])
            for fc in free:
                self._pooled_sids.pop(fc.sid, None)
        if c is not None:
            self.close_quietly(c.sid)
        for fc in free:
            self.close_quietly(fc.sid)

    def pooled_count(self, ep: EndPoint) -> int:
        with self._lock:
            return len(self._pool.get(ep, ()))


class _CallState:
    __slots__ = ("cntl", "channel", "meta_template", "body", "done",
                 "deadline_timer", "backup_timer", "sids", "sid_attempts",
                 "tried_servers", "pooled_conns", "short_conns", "rail_obj",
                 "rail_tickets", "rail_fallback_cache")

    def __init__(self, cntl, channel, meta_template, body, done):
        self.cntl = cntl
        self.channel = channel
        self.meta_template = meta_template
        self.body = body
        self.done = done
        self.deadline_timer = None
        self.backup_timer = None
        self.sids: set[int] = set()
        # sid -> the attempt number that wrote on it, recorded at bind
        # time: the failed-socket callback retries a call only if the
        # failed socket still carries its CURRENT attempt (a stale
        # socket's death must not preempt a live retry chain)
        self.sid_attempts: dict[int, int] = {}
        self.tried_servers: list[EndPoint] = []
        # device-array payload deferred to _issue: staged over ICI when the
        # selected server advertises a device (ici/rail.py), host-serialized
        # only as the fallback
        self.rail_obj = None
        self.rail_tickets: list[str] = []
        self.rail_fallback_cache = None  # (body, tensor_header) once encoded
        # connections this call checked out (pooled) or owns (short); given
        # back / closed at completion — late replies are matched by cid, so
        # recycling before a stale attempt answers is safe
        self.pooled_conns: list[_ClientConn] = []
        self.short_conns: list[_ClientConn] = []


class CallManager:
    """Global pending-call table keyed by correlation id; completes calls
    exactly once across responses/timeouts/socket failures/retries (the role
    OnVersionedRPCReturned plays, controller.cpp:593)."""

    _instance = None
    _instance_lock = threading.Lock()

    @classmethod
    def instance(cls) -> "CallManager":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def __init__(self):
        self._lock = threading.Lock()
        self._pending: dict[int, _CallState] = {}
        self._by_sid: dict[int, set[int]] = {}

    # ---- registration ----

    def register(self, st: _CallState) -> None:
        with self._lock:
            self._pending[st.cntl.correlation_id] = st

    def bind_socket(self, cid: int, sid: int,
                    attempt: int = 0) -> None:
        with self._lock:
            st = self._pending.get(cid)
            if st is not None:
                st.sids.add(sid)
                # latest attempt wins: a retry re-using the same healthy
                # socket moves the sid's ownership to the new attempt
                st.sid_attempts[sid] = attempt
                self._by_sid.setdefault(sid, set()).add(cid)

    def _unregister(self, cid: int) -> Optional[_CallState]:
        with self._lock:
            st = self._pending.pop(cid, None)
            if st is not None:
                for sid in st.sids:
                    s = self._by_sid.get(sid)
                    if s is not None:
                        s.discard(cid)
                        if not s:
                            del self._by_sid[sid]
            return st

    # ---- events ----

    def on_message(self, sid: int, kind: int, meta_bytes: bytes, body) -> None:
        if kind != MSG_TRPC:
            return
        try:
            meta = M.RpcMeta.decode(meta_bytes)
        except ValueError:
            return
        if meta.msg_type == M.MSG_RESPONSE:
            self._on_response(meta, body)
        elif meta.msg_type in (M.MSG_STREAM_DATA, M.MSG_STREAM_FEEDBACK,
                               M.MSG_STREAM_CLOSE):
            from brpc_tpu.rpc.stream import StreamRegistry
            StreamRegistry.instance().on_frame(sid, meta, body)

    def on_fast_response(self, sid: int, cid: int, attempt: int,
                         error_code: int, error_text: str, compress: int,
                         content_type: str, attachment_size: int,
                         body) -> None:
        """Natively pre-parsed response (net/rpc.h via _fastrpc): no
        Python TLV walk; the body is an IOBuf-backed read-only memoryview
        (zero copy — pins the blocks while referenced).  Fast metas carry
        cid/attempt/error/compress/content_type/attachment_size — anything
        richer (streams, tensor headers, user fields) arrives via
        on_message with a full decode."""
        meta = M.RpcMeta(
            msg_type=M.MSG_RESPONSE,
            correlation_id=cid,
            attempt=attempt,
            error_code=error_code,
            error_text=error_text,
            compress_type=compress,
            content_type=content_type,
            attachment_size=attachment_size,
        )
        self._on_response(meta, body)

    def _on_response(self, meta: M.RpcMeta, body) -> None:
        with self._lock:
            st = self._pending.get(meta.correlation_id)
        if st is None:
            # stale attempt after completion — dropped; a rail ticket riding
            # it must be freed now, not left to the registry TTL
            if meta.user_fields and meta.user_fields.get(M.F_TICKET):
                from brpc_tpu.ici import rail
                rail.withdraw(meta.user_fields[M.F_TICKET])
            return
        cntl = st.cntl
        if meta.error_code != 0:
            # Stale-attempt errors must not touch the live call: only the
            # current attempt may drive retry/completion (the bthread_id
            # version check of the reference).  Success from ANY attempt
            # wins — that's what makes backup requests useful.
            if meta.attempt < cntl.current_attempt:
                return
            if meta.user_fields:
                # fields attached to FAILED completions surface too (the
                # reference packs response user fields on errors as well)
                cntl.response_user_fields = \
                    M.strip_reserved_user_fields(meta.user_fields)
            # versioned, like every other failure path: a concurrent
            # retry claim (failed-write / failed-socket) may already own
            # a newer attempt, and this error response is then stale —
            # it must neither stomp the claimed attempt's state nor
            # finish the call under the live attempt
            if not cntl.set_failed_if_current(meta.attempt,
                                              meta.error_code,
                                              meta.error_text):
                return
            if st.channel._should_retry(st, meta.attempt):
                return  # re-issued under the same cid, next attempt
            if cntl.current_attempt > meta.attempt or cntl.completed:
                return  # a racing path claimed the retry first
            self._finish(st)
            return
        # success: decode body
        rail_ticket = meta.user_fields.get(M.F_TICKET) \
            if meta.user_fields else None
        if rail_ticket is not None:
            # response payload rode ICI: claim the device arrays parked in
            # the rail registry — no body bytes exist to decode
            from brpc_tpu.ici import rail
            try:
                cntl.reset_for_retry()
                cntl.response = rail.claim(rail_ticket)
                cntl.response_attachment = b""
            except Exception as e:
                cntl.set_failed(errors.ERESPONSE,
                                f"cannot claim rail payload: {e}")
            self._finish(st)
            return
        try:
            # fast-path bodies arrive as IOBuf-backed memoryviews (zero
            # copy, _fastrpc FastBody); slicing memoryviews stays zero-copy
            raw = body if isinstance(body, (bytes, memoryview)) \
                else body.to_bytes()
            att_size = meta.attachment_size
            payload = raw[: len(raw) - att_size] if att_size else raw
            # attachments keep the documented bytes contract (handlers
            # .decode()/.startswith() them); materialize off the view
            cntl.response_attachment = bytes(raw[len(raw) - att_size:]) \
                if att_size else b""
            payload = decompress(payload, meta.compress_type)
            serializer = getattr(cntl, "_response_serializer", None) or \
                get_serializer(meta.content_type or "raw")
            cntl.reset_for_retry()
            cntl.response = serializer.decode(payload, meta.tensor_header)
            if meta.user_fields:
                # surface server-set user fields, minus transport keys
                cntl.response_user_fields = \
                    M.strip_reserved_user_fields(meta.user_fields)
            if meta.stream_id and cntl._stream is not None:
                sbuf = meta.user_fields.get(M.F_SBUF)
                if sbuf:
                    cntl._stream.peer_buf_size = int(sbuf)
                sdev = meta.user_fields.get(M.F_SDEV)
                if sdev:
                    # the server's EXPLICIT stream advertisement wins
                    # over the pre-bind unary-map guess — the accepting
                    # handler may have picked a different device than
                    # the server-wide ici_device
                    from brpc_tpu.ici import rail
                    dev = rail.device_from_wire(sdev)
                    if dev is not None:
                        cntl._stream.peer_device = dev
                cntl._stream.set_remote(meta.stream_id)
        except Exception as e:  # bad response
            cntl.set_failed(errors.ERESPONSE, f"cannot decode response: {e}")
        self._finish(st)

    def on_socket_failed(self, sid: int, err: int) -> None:
        with self._lock:
            cids = list(self._by_sid.pop(sid, ()))
            states = [(self._pending[c],
                       self._pending[c].sid_attempts.get(sid, 0))
                      for c in cids if c in self._pending]
        for st, owner in states:
            # the failed socket carries attempt `owner`.  If a newer
            # attempt already owns the call (the failed-write path
            # claimed the retry first, or a backup request is in
            # flight), this death is STALE: acting on it would stomp
            # the live attempt's state and burn a second retry —
            # chaos-pinned as the cluster-retry flake where the doomed
            # extra retry excluded every server and failed a call whose
            # live attempt was about to succeed.  The versioned
            # set_failed runs FIRST (the retry policy reads error_code)
            # and doubles as the staleness gate.
            if not st.cntl.set_failed_if_current(
                    owner, errors.EFAILEDSOCKET,
                    f"socket failed (errno {err})"):
                continue
            if st.channel._should_retry(st, owner):
                continue
            if st.cntl.current_attempt == owner and not st.cntl.completed:
                self._finish(st)

    def on_deadline(self, cid: int) -> None:
        self._fail_pending(cid, errors.ERPCTIMEDOUT, "deadline exceeded",
                           cancel_deadline=False)

    def cancel(self, cid: int) -> bool:
        """StartCancel analog (reference example/cancel_c++): complete the
        call NOW with ECANCELED; a late server response is dropped by the
        (correlation_id, attempt) versioning like any stale attempt.
        Returns False if the call already completed (including losing the
        race to a concurrent success)."""
        return self._fail_pending(cid, errors.ECANCELED,
                                  "canceled by caller")

    def _fail_pending(self, cid: int, code: int, text: str,
                      cancel_deadline: bool = True) -> bool:
        """Shared deadline/cancel path.  The error is applied INSIDE
        _finish, after winning the exactly-once completion race — setting
        it first would corrupt a concurrently-arriving success response's
        state (and misreport the failure as applied)."""
        with self._lock:
            st = self._pending.get(cid)
        if st is None:
            return False
        return self._finish(st, cancel_deadline=cancel_deadline,
                            fail=(code, text))

    def _finish(self, st: _CallState, cancel_deadline: bool = True,
                fail: tuple[int, str] | None = None) -> bool:
        if not st.cntl._try_complete():
            return False
        if fail is not None:
            st.cntl.set_failed(*fail)
        self._unregister(st.cntl.correlation_id)
        t = Transport.instance()
        if cancel_deadline and st.deadline_timer is not None:
            t.cancel(st.deadline_timer)
        if st.backup_timer is not None:
            t.cancel(st.backup_timer)
        cntl = st.cntl
        import time
        cntl.latency_us = int(time.monotonic() * 1e6) - cntl._start_us
        if st.rail_tickets:
            # free staged payloads of attempts the server never claimed
            # (timeouts, failed sockets); claim is an atomic pop, so a
            # concurrently-claiming server wins and this no-ops
            from brpc_tpu.ici import rail
            for ticket in st.rail_tickets:
                rail.withdraw(ticket)
            st.rail_tickets.clear()
        # recycle per-call connections (pooled back to the free list,
        # short closed — ConnectionType semantics, protocol.h:161-180)
        if st.pooled_conns:
            smap = SocketMap.instance()
            for c in st.pooled_conns:
                smap.return_pooled(c)
            st.pooled_conns.clear()
        if st.short_conns:
            smap = SocketMap.instance()
            for c in st.short_conns:
                smap.close_quietly(c.sid)
            st.short_conns.clear()
        st.channel._on_call_end(st)
        if st.done is not None:
            try:
                st.done(cntl)
            except Exception:  # pragma: no cover
                import traceback
                traceback.print_exc()
        if cntl._done_event is not None:
            cntl._done_event.set()
        return True


class Channel:
    """Client channel to one server or a cluster (with a load balancer)."""

    def __init__(self, address: str | EndPoint | None = None,
                 options: ChannelOptions | None = None, **kw):
        self.options = options or ChannelOptions(**kw)
        self._lb = None
        self._ns_thread = None
        self._endpoint: Optional[EndPoint] = None
        if address is not None:
            self.init(address, self.options.load_balancer)

    # reference Channel::Init(addr, lb_name, opts)
    def init(self, address: str | EndPoint, load_balancer: str = "") -> "Channel":
        if isinstance(address, EndPoint):
            self._endpoint = address
            return self
        if "://" in address:
            from brpc_tpu.policy.naming import start_naming_service
            from brpc_tpu.policy.load_balancer import create_load_balancer
            self._lb = create_load_balancer(load_balancer or "rr")
            self._ns_thread = start_naming_service(address, self._lb)
        else:
            self._endpoint = str2endpoint(address)
        return self

    # ---- server selection (LB hook) ----

    def _select_server(self, st: _CallState) -> Optional[EndPoint]:
        if self._lb is not None:
            # exact exclusion of every tried server (the ExcludedServers
            # role, excluded_servers.h; a plain set — no capacity bound —
            # so high-retry calls never revisit a failed replica)
            return self._lb.select_server(
                exclude=set(st.tried_servers),
                request_code=st.cntl.request_code)
        return self._endpoint

    def _on_call_end(self, st: _CallState) -> None:
        if not st.tried_servers:
            return
        # Every select_server() gets exactly one feedback (LA balancers
        # track inflight); losing/failed attempts report as socket errors.
        if self._lb is not None:
            for ep in st.tried_servers[:-1]:
                self._lb.feedback(ep, errors.EFAILEDSOCKET, 0)
            self._lb.feedback(st.tried_servers[-1], st.cntl.error_code,
                              st.cntl.latency_us)
        # feed the circuit breaker (reference OnCallEnd, circuit_breaker.h);
        # the cluster guard lets ClusterRecoverPolicy veto isolation when
        # too few healthy servers would remain (cluster_recover_policy.h)
        from brpc_tpu.policy.circuit_breaker import global_breaker
        breaker = global_breaker()
        guard = self._cluster_guard()
        for ep in st.tried_servers[:-1]:
            if ep.scheme == "tcp":
                breaker.on_call_end(ep, errors.EFAILEDSOCKET,
                                    cluster=guard)
        last = st.tried_servers[-1]
        if last.scheme == "tcp":
            breaker.on_call_end(last, st.cntl.error_code,
                                latency_us=st.cntl.latency_us,
                                cluster=guard)

    def _cluster_guard(self):
        """ClusterRecoverPolicy guard bound to this channel's server view
        (None for single-server channels — there is no cluster to
        protect)."""
        if self._lb is None:
            return None
        policy = self.options.cluster_recover_policy
        if policy is None:
            return None
        from brpc_tpu.policy.cluster_recover_policy import \
            _ChannelClusterGuard
        return _ChannelClusterGuard(policy, self._lb)

    # ---- the call path ----

    def call(self, service: str, method_name: str, request: Any = b"",
             cntl: Controller | None = None,
             done: Callable[[Controller], None] | None = None,
             serializer: str = "raw", response_serializer: str | None = None,
             _sync_join: bool = False) -> Controller:
        """Issue an RPC.  With done=None this is async-with-join: the
        returned controller has an event; use .join() or call_sync()."""
        import time
        cntl = cntl or Controller()
        opts = self.options
        if cntl.timeout_ms is None:
            cntl.timeout_ms = opts.timeout_ms
        if cntl.max_retry is None:
            cntl.max_retry = opts.max_retry
        if cntl.backup_request_ms is None:
            cntl.backup_request_ms = opts.backup_request_ms
        cntl.correlation_id = next(_cid_counter)
        cntl._start_us = int(time.monotonic() * 1e6)
        if done is None:
            cntl._done_event = OneShotEvent()

        ser = get_serializer(serializer)
        rail_obj = None
        if ser.name == "tensor" and not cntl.request_attachment:
            # attachments ride the socket body; mixing them with a railed
            # payload would drop them — such calls stay on the host path
            from brpc_tpu.ici import rail
            if rail.railable(request):
                # Defer serialization: the payload may ride ICI instead of
                # the socket, decided per attempt once the server is known
                # (the CutFromIOBufList slot — socket.cpp:1751-1757).
                rail_obj = request
        if rail_obj is None:
            body, tensor_header = ser.encode(request)
            body = compress(body, cntl.compress_type)
        else:
            body, tensor_header = b"", b""
        meta = M.RpcMeta(
            msg_type=M.MSG_REQUEST,
            correlation_id=cntl.correlation_id,
            service=service,
            method=method_name,
            compress_type=cntl.compress_type,
            timeout_ms=cntl.timeout_ms or 0,
            content_type=ser.name,
            tensor_header=tensor_header,
        )
        if cntl.user_fields:
            # caller-supplied opaque metadata (request_user_fields slot);
            # copied so a reused Controller can't mutate an issued frame.
            # ONE shared validation (meta.normalize_user_fields): clean
            # str keys, reserved transport keys rejected — a spoofed
            # rail ticket would make the server claim device blocks
            # instead of decoding the body
            meta.user_fields.update(
                M.normalize_user_fields(cntl.user_fields))
        # the client-side response serializer: typed instances (e.g. a
        # PbSerializer bound to a generated message class) must decode the
        # response locally — the wire's content_type can only name the
        # generic codec.  Deliberately NOT a user field: nothing consumes
        # it on the wire, and any user field disqualifies the call from
        # the native fast-send path.
        if response_serializer:
            cntl._response_serializer = get_serializer(response_serializer)
        # credential is generated per ATTEMPT in _issue (replay-tracking
        # authenticators reject reused nonces), not here
        if cntl.request_attachment:
            meta.attachment_size = len(cntl.request_attachment)
            body = body + cntl.request_attachment

        # stream riding this RPC (stream_create was called with this cntl)
        stream = getattr(cntl, "_stream", None)
        if stream is not None:
            meta.stream_id = stream.stream_id
            meta.user_fields[M.F_SBUF] = str(stream.max_buf_size)
            if stream.device is not None:
                # advertise OUR tensor receive device (rail settings);
                # the embedded process token scopes it to this process
                from brpc_tpu.ici import rail
                meta.user_fields[M.F_SDEV] = rail.device_advert(
                    stream.device)

        # rpcz span (the sampled bit rides a meta flag so the callee
        # inherits the trace-root decision instead of re-rolling)
        from brpc_tpu.rpcz import current_trace_ctx
        tid, sid_, smp = current_trace_ctx()
        meta.trace_id = cntl.trace_id = tid
        meta.span_id = cntl.span_id = sid_
        if tid and smp:
            meta.flags |= M.FLAG_TRACE_SAMPLED

        st = _CallState(cntl, self, meta, body, done)
        st.rail_obj = rail_obj
        mgr = CallManager.instance()
        mgr.register(st)

        t = Transport.instance()
        if cntl.timeout_ms and cntl.timeout_ms > 0:
            if _sync_join:
                # call_sync joins immediately: the joining thread IS the
                # deadline timer (join() computes the remaining budget from
                # _start_us and fires on_deadline itself) — saves a native
                # timer arm+cancel per call on the hot path.  Plain call()
                # users may never join, so they keep the native timer.
                cntl._sync_deadline = True
            else:
                cid = cntl.correlation_id
                st.deadline_timer = t.schedule(cntl.timeout_ms / 1e3,
                                               lambda: mgr.on_deadline(cid))
        if cntl.backup_request_ms and cntl.backup_request_ms > 0:
            st.backup_timer = t.schedule(cntl.backup_request_ms / 1e3,
                                         lambda: self._issue_backup(st))
        self._issue(st)
        return cntl

    def call_sync(self, service: str, method_name: str, request: Any = b"",
                  serializer: str = "raw", **kw) -> Any:
        cntl = kw.pop("cntl", None)
        cntl = self.call(service, method_name, request, cntl=cntl,
                         serializer=serializer, _sync_join=True, **kw)
        cntl.join()
        cntl.raise_if_failed()
        return cntl.response

    def _issue(self, st: _CallState) -> None:
        """Send the current attempt.  On immediate failure, walk the retry
        path (IssueRPC, controller.cpp:1042)."""
        cntl = st.cntl
        mgr = CallManager.instance()
        # the attempt number THIS _issue call issues: every failure
        # below is versioned against it, so a stale path (a concurrent
        # retry already owns a newer attempt) can neither overwrite the
        # live attempt's state nor finish the call under it
        attempt = cntl.current_attempt
        ep = self._select_server(st)
        if ep is None:
            if cntl.set_failed_if_current(attempt, errors.ENODATA,
                                          "no available server"):
                mgr._finish(st)
            return
        st.tried_servers.append(ep)
        cntl.remote_side = str(ep)
        try:
            smap = SocketMap.instance()
            if self.options.tls_context is not None:
                # NS/LB channels resolve endpoints dynamically: register
                # TLS for whichever server this attempt selected BEFORE
                # the connection is (possibly) created
                smap.set_endpoint_tls(
                    ep, self.options.tls_context,
                    self.options.tls_server_hostname or ep.host)
            ctype = self.options.connection_type
            if ctype == "pooled":
                conn = smap.get_pooled(ep)
                st.pooled_conns.append(conn)
            elif ctype == "short":
                conn = smap.make_short(ep)
                st.short_conns.append(conn)
            else:
                conn = smap.get_connection(ep)
        except (ConnectionError, OSError):
            # versioned set BEFORE the retry check (the retry policy
            # reads error_code); a False return means a newer attempt
            # owns the call and this refusal is stale
            if not cntl.set_failed_if_current(attempt, errors.ECONNREFUSED,
                                              f"cannot connect to {ep}"):
                return
            if self._should_retry(st, attempt):
                return
            if cntl.current_attempt == attempt and not cntl.completed:
                mgr._finish(st)
            return
        meta = st.meta_template
        meta.attempt = cntl.current_attempt
        if st.rail_obj is not None:
            self._prepare_rail_attempt(st, ep)
        if self.options.auth is not None:
            # fresh credential per attempt: replay-tracking authenticators
            # (HmacAuthenticator) reject a reused nonce, so retries and
            # backup requests must not resend the first attempt's
            meta.auth = self.options.auth.generate_credential()
        mgr.bind_socket(cntl.correlation_id, conn.sid, attempt)
        stream = getattr(cntl, "_stream", None)
        if stream is not None and not stream.connected:
            if stream.peer_device is None:
                # same slide-under decision the rail makes for unary
                # payloads: an advertised server device means tensor
                # writes ride ICI from the first write, before the
                # settings response arrives.  Resolve BEFORE bind —
                # bind flushes pending writes, which must already know
                # their transport
                from brpc_tpu.ici import rail
                stream.peer_device = rail.lookup(ep)
            stream.bind(conn.sid)
        # `attempt` (captured at entry) versions the write: failing the
        # socket below can run the failed-socket callback SYNCHRONOUSLY
        # or on the transport thread, whose retry path claims the next
        # attempt — after which THIS frame's failure is stale and must
        # stay silent (the reference's bthread_id versioning,
        # OnVersionedRPCReturned; chaos-pinned: a stale path that kept
        # going either finished the call with no response or issued a
        # duplicate attempt)
        if (not meta.auth and not meta.trace_id and not meta.span_id
                and not meta.stream_id and not meta.tensor_header
                and not meta.user_fields and not meta.attachment_size):
            # simple request: meta packed + framed natively
            rc = Transport.send_request(
                conn.sid, meta.correlation_id, meta.attempt, meta.service,
                meta.method, meta.timeout_ms, meta.compress_type,
                meta.content_type, st.body)
        else:
            rc = Transport.instance().write_frame(conn.sid, meta.encode(),
                                                  st.body)
        if rc != 0:
            if rc == -2:
                # native write-queue bound tripped (Socket::Write -2):
                # the peer is reading too slowly for this call's bytes
                # (the socket is healthy — keep it cached).  The guard
                # is ATOMIC under the completion lock: an unlocked
                # check-then-act here could still stomp a concurrently
                # completing call's state
                cntl.set_failed_if_current(attempt, errors.EOVERCROWDED,
                                           "socket write queue overcrowded")
            else:
                cntl.set_failed_if_current(attempt, errors.EFAILEDSOCKET,
                                           "write failed")
                if self.options.connection_type == "single":
                    # the socket is dying but its failed-callback
                    # cleanup may still be in flight on another thread:
                    # evict the cached mapping NOW so the retry below
                    # reconnects instead of re-checking out the same
                    # dying connection and burning every attempt on it
                    smap.evict(ep, conn.sid)
                # and make sure the socket IS failed: a real rc=-1 means
                # it already is (a no-op then), but an evicted-yet-open
                # socket (e.g. an injected plain write error) would leak
                # its fd + handler entries forever.  May synchronously
                # hand the call to the failed-callback's retry path.
                Transport.instance().close(conn.sid, 0)
            if cntl.current_attempt > attempt or cntl.completed:
                return   # a newer attempt or a completion owns the call
            if self._should_retry(st, attempt):
                return
            if cntl.current_attempt > attempt or cntl.completed:
                return   # a racing path claimed the retry first
            mgr._finish(st)

    def _prepare_rail_attempt(self, st: _CallState, ep: EndPoint) -> None:
        """Decide, per attempt, whether the device-array payload rides ICI
        (server advertised a device: stage + transfer + deposit, frame
        carries a ticket) or falls back to host serialization.  Mirrors how
        the reference picks RdmaEndpoint vs the fd per socket at write
        time (socket.cpp:1751-1757)."""
        from brpc_tpu.ici import rail
        meta = st.meta_template
        meta.user_fields.pop(rail.F_TICKET, None)
        meta.user_fields.pop(rail.F_SRC_DEV, None)
        dev = rail.lookup(ep)
        if dev is not None:
            try:
                ticket = rail.ship(st.rail_obj, dev)
            except Exception:
                dev = None  # pool exhausted / transfer failed: host fallback
            else:
                st.rail_tickets.append(ticket)
                meta.user_fields[rail.F_TICKET] = ticket
                meta.user_fields[rail.F_SRC_DEV] = str(
                    rail.source_device(st.rail_obj).id)
                meta.tensor_header = b""
                st.body = b""
                return
        rail.rail_fallbacks.add(1)
        if st.rail_fallback_cache is None:
            ser = get_serializer("tensor")
            body, tensor_header = ser.encode(st.rail_obj)
            st.rail_fallback_cache = (compress(body, st.cntl.compress_type),
                                      tensor_header)
        st.body, meta.tensor_header = st.rail_fallback_cache

    def _should_retry(self, st: _CallState,
                      owner_attempt: int | None = None) -> bool:
        """If allowed, claim the next attempt and re-issue.  Returns
        True when a retry was started (the call stays pending).  The
        claim is ATOMIC against the attempt version (`owner_attempt`,
        defaulting to the current attempt): of two failure paths racing
        to retry the same attempt, exactly one wins — the loser must
        re-check attempt/completion before finishing the call."""
        cntl = st.cntl
        if cntl.completed:
            return False
        policy = self.options.retry_policy or DEFAULT_RETRY_POLICY
        if cntl.current_attempt >= (cntl.max_retry or 0):
            return False
        if not policy.do_retry(cntl):
            return False
        owner = cntl.current_attempt if owner_attempt is None \
            else owner_attempt
        if not cntl.claim_retry(owner):
            return False
        self._issue(st)
        return True

    def _issue_backup(self, st: _CallState) -> None:
        """Backup request: race a second attempt; first response wins
        (channel.cpp:403-409)."""
        cntl = st.cntl
        if cntl.completed:
            return
        if cntl.current_attempt >= (cntl.max_retry or 0):
            return  # max_retry=0 disables backups too (single attempt only)
        if not cntl.claim_backup():
            return
        self._issue(st)
