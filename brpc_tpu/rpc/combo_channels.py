"""Combo channels (reference parallel_channel.{h,cpp},
selective_channel.{h,cpp}, partition_channel.{h,cpp}; SURVEY.md §2.5).

  ParallelChannel   one call fans out to N sub-channels; CallMapper slices
                    or clones the request per sub-channel, ResponseMerger
                    folds sub-responses, fail_limit bounds tolerated
                    failures (parallel_channel.h:94-110).
  SelectiveChannel  channel-of-channels with its own balancer; retries a
                    DIFFERENT sub-channel on failure (selective_channel.h).
  PartitionChannel  shards requests over partitioned servers via a
                    PartitionParser on server tags (partition_channel.h).

TPU-native lowering: when every sub-channel targets an ICI endpoint in the
local mesh, ParallelChannel/PartitionChannel execute as ONE jitted
shard_map over the device mesh — the fan-out becomes a broadcast/shard and
the merge becomes a collective (psum / all_gather), never touching sockets
(SURVEY.md §5.8 target).  See brpc_tpu/ici/collective.py.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Optional, Sequence

from brpc_tpu import errors
from brpc_tpu.rpc.channel import Channel, ChannelOptions
from brpc_tpu.rpc.controller import Controller, OneShotEvent


# CollectiveGroups (and the jitted programs they cache) are shared across
# ParallelChannel instances: one compile per (device set, service fn).
_collective_groups: dict[tuple, Any] = {}
_collective_groups_lock = threading.Lock()


def _collective_group_for(devices):
    """Group over EXACTLY the chips the channels target (never 'the first
    N devices' — the caller may address chips 4..7)."""
    import numpy as _np
    from jax.sharding import Mesh
    from brpc_tpu.ici.collective import CollectiveGroup
    key = tuple(d.id for d in devices)
    with _collective_groups_lock:
        g = _collective_groups.get(key)
        if g is None:
            g = CollectiveGroup(Mesh(_np.array(devices), ("chip",)))
            _collective_groups[key] = g
        return g


class SubCall:
    """What CallMapper returns for one sub-channel: its request (or SKIP)."""

    __slots__ = ("request", "skip")

    def __init__(self, request: Any = None, skip: bool = False):
        self.request = request
        self.skip = skip

    @classmethod
    def skip_call(cls) -> "SubCall":
        return cls(skip=True)


class CallMapper:
    """Map(channel_index, request) -> SubCall (parallel_channel.h:94)."""

    def map(self, channel_index: int, nchannels: int, request: Any) -> SubCall:
        return SubCall(request)   # default: broadcast the same request


class ResponseMerger:
    """merge(responses) -> merged response.  Default returns the list."""

    def merge(self, responses: list) -> Any:
        return responses


class SumMerger(ResponseMerger):
    """Elementwise sum — lowered to psum when the fan-out is collective."""

    def merge(self, responses: list) -> Any:
        out = responses[0]
        for r in responses[1:]:
            out = out + r
        return out


class ParallelChannel:
    def __init__(self, fail_limit: int = 0,
                 call_mapper: CallMapper | None = None,
                 response_merger: ResponseMerger | None = None):
        self._channels: list[tuple[Channel, CallMapper | None]] = []
        self.fail_limit = fail_limit        # 0 = tolerate none
        self.call_mapper = call_mapper or CallMapper()
        self.response_merger = response_merger or ResponseMerger()

    def add_channel(self, channel: Channel,
                    call_mapper: CallMapper | None = None) -> "ParallelChannel":
        self._channels.append((channel, call_mapper))
        return self

    @property
    def channel_count(self) -> int:
        return len(self._channels)

    def _all_ici(self) -> bool:
        """Lowerable iff every sub-channel is ICI AND they target distinct
        devices (duplicate chips are a legitimate per-channel fan-out that
        a collective cannot express)."""
        from brpc_tpu.ici.channel import IciChannel
        if not self._channels or not all(
                isinstance(ch, IciChannel) for ch, _ in self._channels):
            return False
        ids = [ch.device.id for ch, _ in self._channels]
        return len(set(ids)) == len(ids)

    def _call_lowered(self, service: str, method: str, request: Any,
                      cntl: Controller,
                      done: Callable | None) -> Controller:
        """All targets are chips in the local mesh: run the fan-out as ONE
        jitted shard_map — broadcast + per-chip service fn + collective
        merge (SURVEY.md §5.8 lowering).  The merge is "sum" when the
        ResponseMerger is SumMerger, else per-chip results are stacked and
        handed to the merger."""
        from brpc_tpu.ici.channel import device_service_registry
        import time
        import jax
        fn = device_service_registry().get((service, method))
        if fn is None:
            cntl.set_failed(errors.ENOMETHOD,
                            f"no device service {service}.{method}")
        else:
            merge = "sum" if isinstance(self.response_merger, SumMerger) \
                else "stack"
            t0 = time.monotonic()
            try:
                group = _collective_group_for(
                    [ch.device for ch, _ in self._channels])
                out = group.parallel_apply(fn, request, merge=merge)
                out = jax.block_until_ready(out)  # real latency + surfaced
                                                  # device-side failures
                if merge == "stack":
                    out = self.response_merger.merge(list(out))
                cntl.response = out
            except Exception as e:
                cntl.set_failed(errors.EINTERNAL,
                                f"collective lowering failed: {e}")
            cntl.latency_us = int((time.monotonic() - t0) * 1e6)
        if done is not None:
            done(cntl)
        if cntl._done_event is not None:
            cntl._done_event.set()
        return cntl

    def call(self, service: str, method: str, request: Any = b"",
             cntl: Controller | None = None, serializer: str = "raw",
             done: Callable[[Controller], None] | None = None) -> Controller:
        cntl = cntl or Controller()
        n = len(self._channels)
        if n == 0:
            cntl.set_failed(errors.ENODATA, "no sub-channels")
            if done:
                done(cntl)
            return cntl
        if self._all_ici() and type(self.call_mapper) is CallMapper and \
                all(m is None for _, m in self._channels):
            # broadcast fan-out over co-located chips with no per-channel
            # request mapping: collective lowering applies — but ONLY for
            # services that tolerate an outer jit wrap (the registry
            # excludes jit=False self-sharding services; those take the
            # per-channel path below)
            from brpc_tpu.ici.channel import device_service_registry
            if device_service_registry().get((service, method)) is not None:
                if done is None:
                    cntl._done_event = OneShotEvent()
                return self._call_lowered(service, method, request, cntl,
                                          done)
        if done is None:
            cntl._done_event = OneShotEvent()

        sub_cntls: list[Optional[Controller]] = [None] * n
        results: list[Any] = [None] * n
        skipped = [False] * n
        state = {"left": 0, "failed": 0}
        lock = threading.Lock()

        def finish():
            fails = state["failed"]
            if fails > self.fail_limit:
                first_err = next((c for c in sub_cntls
                                  if c is not None and c.failed()), None)
                cntl.set_failed(
                    errors.ETOOMANYFAILS,
                    f"{fails}/{n} sub-calls failed"
                    + (f" (first: E{first_err.error_code} "
                       f"{first_err.error_text})" if first_err else ""))
            else:
                ok = [r for i, r in enumerate(results) if not skipped[i]
                      and sub_cntls[i] is not None
                      and not sub_cntls[i].failed()]
                try:
                    cntl.response = self.response_merger.merge(ok)
                except Exception as e:
                    cntl.set_failed(errors.ERESPONSE, f"merge failed: {e}")
            if done is not None:
                done(cntl)
            if cntl._done_event is not None:
                cntl._done_event.set()

        # map first so skips don't count toward `left`
        mapped: list[Optional[SubCall]] = []
        for i, (ch, mapper) in enumerate(self._channels):
            m = (mapper or self.call_mapper).map(i, n, request)
            if m is None or m.skip:
                skipped[i] = True
                mapped.append(None)
            else:
                mapped.append(m)
                state["left"] += 1
        if state["left"] == 0:
            cntl.set_failed(errors.ENODATA, "all sub-calls skipped")
            if done:
                done(cntl)
            if cntl._done_event is not None:
                cntl._done_event.set()
            return cntl

        def make_done(i):
            def _done(sub):
                with lock:
                    if sub.failed():
                        state["failed"] += 1
                    else:
                        results[i] = sub.response
                    state["left"] -= 1
                    last = state["left"] == 0
                if last:
                    finish()
            return _done

        for i, (ch, _mapper) in enumerate(self._channels):
            if skipped[i]:
                continue
            sub = Controller(timeout_ms=cntl.timeout_ms,
                             max_retry=cntl.max_retry)
            sub_cntls[i] = sub
            ch.call(service, method, mapped[i].request, cntl=sub,
                    serializer=serializer, done=make_done(i))
        return cntl

    def call_sync(self, service: str, method: str, request: Any = b"",
                  serializer: str = "raw", **kw) -> Any:
        cntl = self.call(service, method, request, serializer=serializer, **kw)
        cntl.join()
        cntl.raise_if_failed()
        return cntl.response


class SelectiveChannel:
    """Retries a different sub-channel on failure; its own LB over
    sub-channels (selective_channel.h:52-69).

    By default selection is round-robin over the registered
    sub-channels.  With ``lb=`` (any
    :class:`~brpc_tpu.policy.load_balancer.LoadBalancer`, e.g.
    ``prefix_affinity``) and endpoints supplied to ``add_channel``,
    selection is DELEGATED to the balancer — health-check broken
    endpoints are skipped, the circuit breaker's recovery ramp
    applies, and ``request_code`` routes consistently (the cluster
    router's forward path, ISSUE 8).  ``pick``/``feedback`` expose the
    per-attempt machinery to callers (streaming RPCs) that must drive
    each attempt themselves rather than through ``call_sync``."""

    def __init__(self, max_retry: int = 3, lb=None):
        self._channels: list[Channel] = []
        self._endpoints: list = []       # parallel to _channels (or None)
        self.max_retry = max_retry
        self._lb = lb
        self._counter = 0
        self._lock = threading.Lock()

    def add_channel(self, channel: Channel,
                    endpoint=None) -> "SelectiveChannel":
        if endpoint is None:
            endpoint = getattr(channel, "_endpoint", None)
        self._channels.append(channel)
        self._endpoints.append(endpoint)
        if self._lb is not None and endpoint is not None:
            from brpc_tpu.policy.load_balancer import ServerNode
            self._lb.add_server(ServerNode(endpoint))
        return self

    @property
    def channel_count(self) -> int:
        return len(self._channels)

    def _index_of(self, endpoint) -> Optional[int]:
        for i, ep in enumerate(self._endpoints):
            if ep == endpoint:
                return i
        return None

    def pick(self, exclude=None, request_code: Optional[int] = None):
        """One selection: ``(index, channel, endpoint)`` or ``None``
        when nothing is selectable.  ``exclude`` is a set of endpoints
        (lb mode) or indices (round-robin mode) already tried."""
        if self._lb is not None:
            ep = self._lb.select_server(exclude=exclude or set(),
                                        request_code=request_code)
            if ep is None:
                return None
            i = self._index_of(ep)
            if i is None:
                return None
            return i, self._channels[i], ep
        i = self._pick(exclude or set())
        if i is None:
            return None
        return i, self._channels[i], self._endpoints[i]

    def feedback(self, endpoint, error_code: int,
                 latency_us: int = 0, *, breaker: bool = True) -> None:
        """Report one attempt's outcome: the balancer adjusts its
        weights and (with ``breaker=True``) the global circuit breaker
        accumulates the endpoint's error/latency evidence.  Callers
        whose attempt already rode a sub-channel ``call_sync`` pass
        ``breaker=False`` — the channel layer fed the breaker itself,
        and double-counting would halve its isolation thresholds."""
        if endpoint is None:
            return
        if self._lb is not None:
            self._lb.feedback(endpoint, error_code, latency_us)
        if breaker:
            from brpc_tpu.policy.circuit_breaker import global_breaker
            global_breaker().on_call_end(endpoint, error_code, latency_us)

    def _pick(self, exclude: set[int]) -> Optional[int]:
        with self._lock:
            n = len(self._channels)
            for _ in range(n):
                i = self._counter % n
                self._counter += 1
                if i not in exclude:
                    return i
        return None

    def call_sync(self, service: str, method: str, request: Any = b"",
                  serializer: str = "raw", cntl: Controller | None = None) -> Any:
        if not self._channels:
            raise errors.RpcError(errors.ENODATA, "no sub-channels")
        tried: set[int] = set()
        tried_eps: set = set()
        last: Exception | None = None
        max_retry = cntl.max_retry if cntl is not None and \
            cntl.max_retry is not None else self.max_retry
        req_code = cntl.request_code if cntl is not None else None
        for _ in range(min(max_retry + 1, len(self._channels))):
            picked = self.pick(
                exclude=tried_eps if self._lb is not None else tried,
                request_code=req_code)
            if picked is None:
                break
            i, _chan, ep = picked
            if i in tried:
                break     # balancer re-offered an already-tried replica
            tried.add(i)
            if ep is not None:
                tried_eps.add(ep)
            sub = Controller(timeout_ms=cntl.timeout_ms if cntl else None)
            try:
                resp = self._channels[i].call_sync(
                    service, method, request, serializer=serializer,
                    cntl=sub)
                self.feedback(ep, 0, sub.latency_us or 0, breaker=False)
                if cntl is not None:
                    # callers follow the Channel contract: results land on
                    # the controller they passed in
                    cntl.reset_for_retry()
                    cntl.response = sub.response
                    cntl.response_attachment = sub.response_attachment
                    cntl.remote_side = sub.remote_side
                    cntl.latency_us = sub.latency_us
                    cntl.retried_count = len(tried) - 1
                return resp
            except errors.RpcError as e:
                last = e
                self.feedback(ep, e.code, sub.latency_us or 0,
                              breaker=False)
                if cntl is not None:
                    cntl.set_failed(sub.error_code, sub.error_text)
                    cntl.remote_side = sub.remote_side
                    cntl.retried_count = len(tried) - 1
                continue
        raise last or errors.RpcError(errors.ETOOMANYFAILS)


class PartitionParser:
    """tag -> (partition_index, partition_count), e.g. "2/8" like the
    reference's "N/M" scheme (partition_channel.h)."""

    def parse(self, tag: str) -> Optional[tuple[int, int]]:
        try:
            idx, _, cnt = tag.partition("/")
            return int(idx), int(cnt)
        except ValueError:
            return None


class PartitionChannel:
    """One channel per partition, built from ONE naming service whose nodes
    carry partition tags; call() fans out one sub-request per partition via
    a CallMapper that receives the partition index.

    Partitions can also be registered DIRECTLY (``add_partition``) —
    the psserve client path, where the caller computes ownership and
    drives one sub-call per partition itself.  With ``lb=`` (a
    ``create_load_balancer`` spec or a factory returning LoadBalancer
    instances) a partition with several replicas selects through its
    own balancer exactly the way SelectiveChannel does since ISSUE 8:
    ``pick``/``feedback`` expose the per-attempt machinery, health-
    broken replicas are skipped, and the circuit breaker's evidence
    accumulates.  ``call_partitioned`` is the retrying fan-out driver:
    one sub-call per partition, failed partitions re-issued (a replica
    rotation under ``lb=``) up to ``max_retry`` times — callers make
    retries safe with idempotent sub-requests (psserve update_ids).
    NOTE: idempotence-by-id only holds when a partition's replicas
    SHARE the dedup state (one shard object, or replicated applied
    sets) — replicas with independent state will double-apply a
    rotated retry of a mutating sub-call; register independent
    replicas for read traffic only."""

    def __init__(self, partition_count: int,
                 call_mapper: CallMapper | None = None,
                 response_merger: ResponseMerger | None = None,
                 fail_limit: int = 0, lb=None):
        self.partition_count = partition_count
        self._parallel = ParallelChannel(fail_limit, call_mapper,
                                         response_merger)
        self._partitions: dict[int, Channel] = {}
        self._lb_spec = lb

    def _make_lb(self):
        if self._lb_spec is None:
            return None
        if callable(self._lb_spec) and not isinstance(self._lb_spec, str):
            return self._lb_spec()
        from brpc_tpu.policy.load_balancer import create_load_balancer
        return create_load_balancer(self._lb_spec)

    def init(self, naming_url: str, load_balancer: str = "rr",
             parser: PartitionParser | None = None,
             options: ChannelOptions | None = None) -> "PartitionChannel":
        from brpc_tpu.policy.load_balancer import create_load_balancer
        from brpc_tpu.policy.naming import (NamingServiceFilter,
                                            start_naming_service)
        parser = parser or PartitionParser()

        class _PartFilter(NamingServiceFilter):
            def __init__(self, idx, count):
                self.idx = idx
                self.count = count

            def accept(self, node):
                p = parser.parse(node.tag)
                return p is not None and p[0] == self.idx and \
                    p[1] == self.count

        for idx in range(self.partition_count):
            lb = create_load_balancer(load_balancer)
            start_naming_service(naming_url, lb,
                                 _PartFilter(idx, self.partition_count))
            ch = Channel(options=options or ChannelOptions())
            ch._lb = lb
            self._partitions[idx] = ch
            self._parallel.add_channel(ch)
        return self

    def add_partition(self, idx: int, channel: Channel,
                      endpoint=None) -> "PartitionChannel":
        """Register one replica of partition ``idx`` directly (no
        naming service).  A second replica for the same partition
        promotes it to a SelectiveChannel (balancer = ``lb=`` when
        given, round-robin otherwise) so the fan-out retries a
        DIFFERENT replica on failure."""
        if not (0 <= idx < self.partition_count):
            raise ValueError(f"partition {idx} out of range "
                             f"0..{self.partition_count - 1}")
        cur = self._partitions.get(idx)
        if cur is None:
            if self._lb_spec is not None:
                sc = SelectiveChannel(lb=self._make_lb())
                sc.add_channel(channel, endpoint=endpoint)
                self._partitions[idx] = sc
            else:
                self._partitions[idx] = channel
            # keep the ParallelChannel fan-out path coherent with the
            # direct registration (call()/call_sync() still work)
            self._parallel.add_channel(self._partitions[idx])
        elif isinstance(cur, SelectiveChannel):
            cur.add_channel(channel, endpoint=endpoint)
        else:
            sc = SelectiveChannel(lb=self._make_lb())
            sc.add_channel(cur, endpoint=getattr(cur, "_endpoint", None))
            sc.add_channel(channel, endpoint=endpoint)
            self._partitions[idx] = sc
            # swap inside the parallel fan-out list too
            for i, (ch, m) in enumerate(self._parallel._channels):
                if ch is cur:
                    self._parallel._channels[i] = (sc, m)
                    break
        return self

    def channel_for(self, idx: int) -> Optional[Channel]:
        return self._partitions.get(idx)

    def pick(self, idx: int, exclude=None, request_code=None):
        """One replica selection for partition ``idx`` — delegates to
        the partition's SelectiveChannel when it has one (lb mode),
        else returns the partition's only channel."""
        ch = self._partitions.get(idx)
        if ch is None:
            return None
        if isinstance(ch, SelectiveChannel):
            return ch.pick(exclude=exclude, request_code=request_code)
        return 0, ch, getattr(ch, "_endpoint", None)

    def feedback(self, idx: int, endpoint, error_code: int,
                 latency_us: int = 0, *, breaker: bool = True) -> None:
        """Report one sub-call attempt's outcome for partition ``idx``
        (the SelectiveChannel parity surface, ISSUE 8)."""
        ch = self._partitions.get(idx)
        if isinstance(ch, SelectiveChannel):
            ch.feedback(endpoint, error_code, latency_us,
                        breaker=breaker)

    # ---- the retrying sub-call-per-partition driver ----

    def _issue_one(self, idx, ch, req, cntl, service, method,
                   serializer, tried_eps, failed, pending) -> None:
        """Issue one partition's attempt without blocking (the round
        driver joins later).  lb-mode partitions pick a replica with
        rotation; once every replica was tried this rotation, the
        exclusion set RESETS so the retry budget stays max_retry+1
        attempts (the old per-attempt driver used a fresh exclusion
        set per attempt), not the replica count."""
        if isinstance(ch, SelectiveChannel):
            picked = ch.pick(exclude=tried_eps[idx])
            if picked is None and tried_eps[idx]:
                tried_eps[idx].clear()
                picked = ch.pick(exclude=tried_eps[idx])
            if picked is None:
                failed.setdefault(
                    idx, errors.RpcError(errors.ENODATA,
                                         "no selectable replica left"))
                return
            _i, sub_ch, ep = picked
            # exclusion keys match pick()'s contract: endpoints in lb
            # mode, channel indices in round-robin mode
            tried_eps[idx].add(ep if ch._lb is not None else _i)
            # _sync_join: the round driver's join IS the deadline timer
            # (the call_sync discipline) — no native timer arm+cancel
            # per sub-call
            sub_ch.call(service, method, req, cntl=cntl,
                        serializer=serializer, _sync_join=True)
            pending.append((idx, cntl, (ch, ep)))
        else:
            ch.call(service, method, req, cntl=cntl,
                    serializer=serializer, _sync_join=True)
            pending.append((idx, cntl, None))

    def call_partitioned(self, service: str, method: str,
                         sub_requests: dict,
                         serializer: str = "json",
                         timeout_ms: Optional[int] = None,
                         max_retry: int = 2,
                         on_retry: Callable | None = None) -> dict:
        """Fan ``sub_requests[idx]`` out as one sub-call per partition
        (concurrently), retrying each failed partition up to
        ``max_retry`` more times — under ``lb=`` every retry rotates to
        a different replica via the partition's balancer, which also
        receives each attempt's outcome.  Returns ``{idx: response}``;
        raises
        ETOOMANYFAILS when any partition exhausts its attempts (callers
        keep retried sub-requests idempotent)."""
        if not sub_requests:
            return {}
        missing = [i for i in sub_requests if i not in self._partitions]
        if missing:
            raise errors.RpcError(errors.ENODATA,
                                  f"no channel for partitions {missing}")

        from brpc_tpu.rpc.channel import RetryPolicy

        # ROUND-BASED ASYNC fan-out (ISSUE 13): every round ISSUES all
        # still-pending sub-calls without blocking (Channel.call with a
        # join handle — no pool thread per partition; the old
        # thread-per-sub-call driver cost ~1ms of GIL-contended wakeups
        # per fan-out on loopback), then joins them in order.  Failed
        # retryable partitions re-issue in the NEXT round, up to
        # max_retry extra rounds — identical attempt/rotation semantics
        # to the per-partition retry loop, batched by round (retries
        # are the exception path; paying round latency there is free).
        # lb-mode partitions (SelectiveChannel) drive pick()/feedback()
        # per attempt — the exposed per-attempt machinery — so replica
        # rotation and balancer/breaker evidence behave exactly as the
        # SelectiveChannel.call_sync loop (breaker fed by the channel
        # layer; feedback(breaker=False)).
        out: dict = {}
        failed: dict = {}
        tried_eps: dict = {idx: set() for idx in sub_requests}
        todo = list(sub_requests)
        for _round in range(max_retry + 1):
            pending = []    # (idx, cntl, endpoint-for-feedback)
            for idx in todo:
                req = sub_requests[idx]
                ch = self._partitions[idx]
                cntl = Controller(timeout_ms=timeout_ms)
                try:
                    self._issue_one(idx, ch, req, cntl, service, method,
                                    serializer, tried_eps, failed,
                                    pending)
                except errors.RpcError as e:
                    failed[idx] = e
                except Exception as e:
                    # an issue-phase bug (encode failure, ...) must not
                    # escape raw and abandon the already-issued
                    # sub-calls un-joined — classify it and keep
                    # draining the round
                    failed[idx] = errors.RpcError(
                        errors.EINTERNAL,
                        f"sub-call issue failed: "
                        f"{type(e).__name__}: {e}")
            todo = []
            for idx, cntl, fb in pending:
                cntl.join()
                if fb is not None:
                    sel, ep = fb
                    sel.feedback(ep, cntl.error_code,
                                 cntl.latency_us or 0, breaker=False)
                if not cntl.failed():
                    out[idx] = cntl.response
                    failed.pop(idx, None)
                    continue
                e = errors.RpcError(cntl.error_code,
                                    cntl.error_text
                                    or errors.describe(cntl.error_code))
                failed[idx] = e
                if e.code not in RetryPolicy.RETRYABLE:
                    # EREQUEST/ENODATA/ENOMETHOD/... are deterministic:
                    # re-issuing the identical sub-call cannot succeed
                    # (reference retry_policy.h semantics)
                    continue
                if _round < max_retry:
                    if on_retry is not None:
                        on_retry(idx, e)   # another attempt follows
                    todo.append(idx)
            if not todo:
                break
        if failed:
            first = next(iter(failed.values()))
            codes = {e.code for e in failed.values()
                     if isinstance(e, errors.RpcError)}
            # one distinct underlying code: surface IT (a caller
            # switching on e.code must see ENODATA for a missing
            # param, not a generic ETOOMANYFAILS); mixed codes keep
            # the aggregate
            code = codes.pop() if len(codes) == 1 \
                else errors.ETOOMANYFAILS
            err = errors.RpcError(
                code,
                f"{len(failed)}/{len(sub_requests)} partitions failed"
                f" (first: partition {next(iter(failed))}: {first})")
            err.failed_partitions = dict(failed)
            err.partial_responses = dict(out)
            raise err
        return out

    def close(self) -> None:
        # the fan-out driver is async (join handles) since ISSUE 13 —
        # no pool to shut down; kept for caller symmetry
        pass

    def call(self, *a, **kw):
        return self._parallel.call(*a, **kw)

    def call_sync(self, *a, **kw):
        return self._parallel.call_sync(*a, **kw)

    @property
    def channel_count(self):
        return self._parallel.channel_count


class DynamicPartitionChannel:
    """Mixes multiple partition schemes living in ONE naming service,
    weighting traffic by each scheme's capacity (reference
    DynamicPartitionChannel, partition_channel.h:120-168): servers tagged
    "0/4".."3/4" and "0/8".."7/8" coexist, and calls pick a scheme with
    probability proportional to its server count, so capacity can migrate
    between schemes by re-tagging servers — no client restart.

    This object IS the naming-service sink (reset_servers), so membership
    changes re-group schemes live, the way the reference's sub-channels
    subscribe to one NamingServiceThread."""

    def __init__(self, call_mapper: CallMapper | None = None,
                 response_merger: ResponseMerger | None = None,
                 fail_limit: int = 0,
                 parser: PartitionParser | None = None,
                 options: ChannelOptions | None = None):
        self.call_mapper = call_mapper
        self.response_merger = response_merger
        self.fail_limit = fail_limit
        self._parser = parser or PartitionParser()
        self._options = options or ChannelOptions()
        self._mu = threading.Lock()
        # scheme (partition_count) -> [servers per partition index]
        self._schemes: dict[int, list[list]] = {}
        self._channels: dict = {}      # endpoint -> single-server Channel
        self._rr = 0
        self._ns_thread = None

    # ---- naming-service sink (NamingServiceActions analog) ----

    def reset_servers(self, nodes) -> None:
        schemes: dict[int, list[list]] = {}
        for n in nodes:
            p = self._parser.parse(n.tag)
            if p is None:
                continue
            idx, cnt = p
            if cnt <= 0 or not (0 <= idx < cnt):
                continue
            parts = schemes.setdefault(cnt, [[] for _ in range(cnt)])
            parts[idx].append(n.endpoint)
        # only schemes with every partition populated are callable
        live = {n.endpoint for n in nodes}
        with self._mu:
            self._schemes = {cnt: parts for cnt, parts in schemes.items()
                             if all(parts)}
            departed = [ep for ep in self._channels if ep not in live]
            for ep in departed:
                del self._channels[ep]
        # evict departed servers' CONNECTIONS too (they're owned by the
        # process-wide SocketMap, not the Channel wrapper) so elastic
        # membership churn doesn't leak sockets
        from brpc_tpu.rpc.channel import SocketMap
        for ep in departed:
            SocketMap.instance().drop(ep)

    def init(self, naming_url: str,
             options: ChannelOptions | None = None
             ) -> "DynamicPartitionChannel":
        if options is not None:
            self._options = options
        from brpc_tpu.policy.naming import start_naming_service
        self._ns_thread = start_naming_service(naming_url, self)
        self._ns_thread.wait_first_resolution()
        return self

    def stop(self) -> None:
        if self._ns_thread is not None:
            self._ns_thread.stop()

    @property
    def scheme_counts(self) -> dict[int, int]:
        with self._mu:
            return {cnt: sum(len(p) for p in parts)
                    for cnt, parts in self._schemes.items()}

    def _channel_for(self, endpoint) -> Channel:
        ch = self._channels.get(endpoint)
        if ch is None:
            ch = Channel(str(endpoint), options=self._options)
            self._channels[endpoint] = ch
        return ch

    def _pick_scheme(self):
        """Weight by scheme capacity = number of servers carrying its tags
        (the dynpart weighting, policy/dynpart_load_balancer.cpp)."""
        import random
        with self._mu:
            if not self._schemes:
                return None, None
            weights = [(cnt, sum(len(p) for p in parts))
                       for cnt, parts in self._schemes.items()]
            total = sum(w for _, w in weights)
            r = random.uniform(0, total)
            acc = 0.0
            for cnt, w in weights:
                acc += w
                if r <= acc:
                    break
            parts = self._schemes[cnt]
            self._rr += 1
            chosen = [p[self._rr % len(p)] for p in parts]
            return cnt, [self._channel_for(ep) for ep in chosen]

    def call(self, service: str, method: str, request: Any = b"",
             cntl: Controller | None = None, serializer: str = "raw",
             done: Callable[[Controller], None] | None = None) -> Controller:
        cnt, chans = self._pick_scheme()
        if chans is None:
            cntl = cntl or Controller()
            cntl.set_failed(errors.ENODATA,
                            "no complete partition scheme resolved")
            if done:
                done(cntl)
            else:
                cntl._done_event = OneShotEvent()
                cntl._done_event.set()
            return cntl
        pc = ParallelChannel(self.fail_limit, self.call_mapper,
                             self.response_merger)
        for ch in chans:
            pc.add_channel(ch)
        return pc.call(service, method, request, cntl=cntl,
                       serializer=serializer, done=done)

    def call_sync(self, service: str, method: str, request: Any = b"",
                  serializer: str = "raw", timeout_s: float = 10.0, **kw):
        cntl = kw.pop("cntl", None) or Controller()
        if cntl.timeout_ms is None:
            # join() only bounds its wait when the controller carries a
            # deadline — without this the timeout_s parameter would be a
            # silent no-op
            cntl.timeout_ms = int(timeout_s * 1000)
        cntl = self.call(service, method, request, cntl=cntl,
                         serializer=serializer, **kw)
        cntl.join()
        cntl.raise_if_failed()
        return cntl.response
