"""Compact self-describing binary codec — the mcpack2pb slot.

Reference: mcpack2pb/ (4,414 LoC: Baidu's mcpack binary format bridged to
protobuf with a protoc code generator).  The TPU build fills the same
design slot — a schema-light compact binary encoding that round-trips to
JSON-shaped values and plugs into the serializer registry (name
"compact") — without replicating Baidu's exact wire format; there are no
legacy mcpack peers to interoperate with.

Wire grammar (all little-endian, varint = LEB128):
  value   = type:u8 payload
  types   0x00 null        0x01 false       0x02 true
          0x03 int (zigzag varint)          0x04 float64
          0x05 str (varint len + utf8)      0x06 bytes (varint len)
          0x07 list (varint count + values)
          0x08 dict (varint count + (str value)*)
Bounded depth guards against stack-abuse payloads (fuzz surface).
"""
from __future__ import annotations

import struct
from typing import Any

MAX_DEPTH = 64


def _w_varint(out: bytearray, n: int) -> None:
    while n >= 0x80:
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n)


def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63) if -(1 << 63) <= n < (1 << 63) else \
        _raise(ValueError("int out of 64-bit range"))


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def _raise(e):
    raise e


def _encode_into(out: bytearray, v: Any, depth: int) -> None:
    if depth > MAX_DEPTH:
        raise ValueError("nesting too deep")
    if v is None:
        out.append(0x00)
    elif v is False:
        out.append(0x01)
    elif v is True:
        out.append(0x02)
    elif isinstance(v, int):
        out.append(0x03)
        _w_varint(out, _zigzag(v))
    elif isinstance(v, float):
        out.append(0x04)
        out += struct.pack("<d", v)
    elif isinstance(v, str):
        raw = v.encode("utf-8")
        out.append(0x05)
        _w_varint(out, len(raw))
        out += raw
    elif isinstance(v, (bytes, bytearray, memoryview)):
        out.append(0x06)
        b = bytes(v)
        _w_varint(out, len(b))
        out += b
    elif isinstance(v, (list, tuple)):
        out.append(0x07)
        _w_varint(out, len(v))
        for e in v:
            _encode_into(out, e, depth + 1)
    elif isinstance(v, dict):
        out.append(0x08)
        _w_varint(out, len(v))
        for k, e in v.items():
            if not isinstance(k, str):
                raise TypeError("compact dict keys must be str")
            raw = k.encode("utf-8")
            _w_varint(out, len(raw))
            out += raw
            _encode_into(out, e, depth + 1)
    else:
        raise TypeError(f"cannot compact-encode {type(v)!r}")


def dumps(v: Any) -> bytes:
    out = bytearray()
    _encode_into(out, v, 0)
    return bytes(out)


class _Reader:
    __slots__ = ("d", "p")

    def __init__(self, data: bytes):
        self.d = data
        self.p = 0

    def u8(self) -> int:
        if self.p >= len(self.d):
            raise ValueError("truncated")
        b = self.d[self.p]
        self.p += 1
        return b

    def varint(self) -> int:
        n = 0
        shift = 0
        while True:
            b = self.u8()
            n |= (b & 0x7F) << shift
            if not (b & 0x80):
                return n
            shift += 7
            if shift > 70:
                raise ValueError("varint overflow")

    def take(self, n: int) -> bytes:
        if n < 0 or self.p + n > len(self.d):
            raise ValueError("truncated")
        v = self.d[self.p:self.p + n]
        self.p += n
        return v

    def value(self, depth: int = 0) -> Any:
        if depth > MAX_DEPTH:
            raise ValueError("nesting too deep")
        t = self.u8()
        if t == 0x00:
            return None
        if t == 0x01:
            return False
        if t == 0x02:
            return True
        if t == 0x03:
            return _unzigzag(self.varint())
        if t == 0x04:
            return struct.unpack("<d", self.take(8))[0]
        if t == 0x05:
            return self.take(self.varint()).decode("utf-8")
        if t == 0x06:
            return self.take(self.varint())
        if t == 0x07:
            n = self.varint()
            if n > len(self.d):  # cannot have more elements than bytes
                raise ValueError("bad list count")
            return [self.value(depth + 1) for _ in range(n)]
        if t == 0x08:
            n = self.varint()
            if n > len(self.d):
                raise ValueError("bad dict count")
            out = {}
            for _ in range(n):
                k = self.take(self.varint()).decode("utf-8")
                out[k] = self.value(depth + 1)
            return out
        raise ValueError(f"unknown compact type 0x{t:02x}")


def loads(data: bytes) -> Any:
    r = _Reader(data)
    v = r.value()
    if r.p != len(data):
        raise ValueError("trailing bytes")
    return v


# ---- json bridge (json2pb/mcpack2pb bridge role) ---------------------------

def compact_to_json(data: bytes) -> str:
    import base64
    import json

    def conv(v):
        if isinstance(v, bytes):
            return {"__bytes__": base64.b64encode(v).decode()}
        if isinstance(v, list):
            return [conv(e) for e in v]
        if isinstance(v, dict):
            return {k: conv(e) for k, e in v.items()}
        return v

    return json.dumps(conv(loads(data)))


def json_to_compact(text: str) -> bytes:
    import base64
    import json

    def conv(v):
        if isinstance(v, dict):
            if set(v) == {"__bytes__"}:
                return base64.b64decode(v["__bytes__"])
            return {k: conv(e) for k, e in v.items()}
        if isinstance(v, list):
            return [conv(e) for e in v]
        return v

    return dumps(conv(json.loads(text)))
