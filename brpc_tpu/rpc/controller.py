"""Controller — per-RPC context and completion state.

Role of the reference's brpc::Controller (controller.h:114; SURVEY.md §2.5):
carries options in (timeout, retries, compression), results out (error code/
text, response, attachment), and owns the call's completion state machine.
The retry/backup versioning trick of bthread_id (each attempt has its own
slot; stale attempts can't complete the call twice) is kept via the
(correlation_id, attempt) pair and a completion lock.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional

from brpc_tpu import errors
from brpc_tpu.rpc import meta as M


class OneShotEvent:
    """threading.Event specialized for exactly-once RPC completion: a
    pre-acquired raw lock released by set().  Half the primitive lock
    operations of Event's Condition dance per sync call — the wait is
    ONE acquire on the completer's release, not an allocate/append/
    reacquire cycle.  set() is called once (the completion path is
    exactly-once via Controller._try_complete); a benign double-set is
    absorbed."""

    __slots__ = ("_lock", "_flag")

    def __init__(self):
        self._lock = threading.Lock()
        self._lock.acquire()
        self._flag = False

    def set(self) -> None:
        if not self._flag:
            self._flag = True
            try:
                self._lock.release()
            except RuntimeError:   # benign double-set race
                pass

    def is_set(self) -> bool:
        return self._flag

    def wait(self, timeout: float | None = None) -> bool:
        if self._flag:
            return True
        if timeout is None:
            acquired = self._lock.acquire()
        else:
            acquired = self._lock.acquire(True, timeout)
        if acquired:
            try:
                self._lock.release()   # pass the baton to other waiters
            except RuntimeError:       # absorbed the same double-set race
                pass                   # set() guards against
        return self._flag


class Controller:
    def __init__(self, *, timeout_ms: Optional[int] = None,
                 max_retry: Optional[int] = None,
                 backup_request_ms: Optional[int] = None,
                 compress_type: int = M.COMPRESS_NONE):
        # ---- client-side options (None = inherit from ChannelOptions) ----
        self.timeout_ms = timeout_ms
        self.max_retry = max_retry
        self.backup_request_ms = backup_request_ms
        self.compress_type = compress_type
        self.request_attachment: bytes = b""
        # consistent-hashing affinity key (reference
        # Controller::set_request_code): c_* balancers route by it
        self.request_code: Optional[int] = None
        # opaque per-request key/values riding the RpcMeta (reference
        # Controller::request_user_fields, baidu_rpc_meta.proto
        # user_fields); server handlers read cntl.request_meta.user_fields
        # — VALUES arrive there as bytes (wire convention, meta.py decode)
        self.user_fields: dict = {}
        # the response direction (Controller::response_user_fields):
        # server handlers SET this; the client reads it after completion
        # (values arrive as bytes, internal transport keys stripped).
        # Carried on native TRPC responses — including failed ones; gRPC
        # responses do not carry it (h2 trailers are status-only here)
        self.response_user_fields: dict = {}

        # ---- result state ----
        self.error_code: int = 0
        self.error_text: str = ""
        self.response: Any = None
        self.response_attachment: bytes = b""
        # server-side: the request body's wire size (set in the decode
        # phase) — handlers doing per-serializer wire-bytes accounting
        # (psserve_wire_bytes_*) read it instead of re-encoding
        self.request_body_size: int = 0
        self.trace_id: int = 0
        self.span_id: int = 0

        # ---- call bookkeeping ----
        self.correlation_id: int = 0
        self.current_attempt: int = 0
        self.retried_count: int = 0
        self.remote_side: str = ""
        self.latency_us: int = 0
        self._start_us: int = 0
        self._done_event: Optional["OneShotEvent"] = None
        self._done_cb: Optional[Callable[["Controller"], None]] = None
        self._completed = False
        self._lock = threading.Lock()
        self._timeout_timer = None
        self._backup_timer = None

        # ---- server-side state ----
        self.is_server_side = False
        self.request_meta: Optional[M.RpcMeta] = None
        # gRPC only: the request's h2 headers/metadata (":path",
        # "authorization", caller metadata...) — the reference exposes
        # gRPC metadata to handlers the same way
        self.request_headers: dict = {}
        self.peer_sid: int = 0
        # pooled per-request data (ServerOptions.session_data_factory)
        self.session_data = None
        # stream riding this RPC (see rpc/stream.py)
        self._stream = None
        # deferred completion (the reference's done Closure: SendRpcResponse
        # runs when the handler calls done->Run(), not when it returns —
        # baidu_rpc_protocol.cpp:398 passes done into svc->CallMethod)
        self._server_done: Optional[Callable[[Any], None]] = None
        self._done_factory: Optional[Callable[[], Callable]] = None
        self._deferred = False

    def accept_stream(self, handler=None, max_buf_size: int = 2 * 1024 * 1024,
                      device=None):
        """Server handler: accept the stream the client attached.
        `device` = where this side receives tensor payloads (rail)."""
        from brpc_tpu.rpc.stream import stream_accept
        return stream_accept(self, handler, max_buf_size, device=device)

    def defer(self) -> Callable[[Any], None]:
        """Server handler: switch this RPC to asynchronous completion.

        Returns a one-shot ``done(response)`` callable; the handler may
        return immediately (its return value is ignored) and any thread may
        later call ``done(response)`` to run the response path.  Until then
        the RPC is in-flight as a parked closure — data, not a thread —
        which is how 10k concurrent in-flight RPCs are served by a small
        worker pool (reference: brpc's done Closure + bthread parking;
        SURVEY.md §2.2, VERDICT r2 task 3)."""
        with self._lock:
            if not self.is_server_side or (self._server_done is None
                                           and self._done_factory is None):
                # also the LATE-defer case: inline completion consumed
                # the factory, so a handler that already responded and
                # defers afterwards fails loudly instead of silently
                # double-sending
                raise RuntimeError("defer() is only valid inside a server "
                                   "handler invocation")
            self._deferred = True
            if self._server_done is None:
                # the done closure (once-guard lock included) is built ON
                # DEMAND: the common non-deferred path completes inline
                # without allocating it per request.  One-shot: the
                # factory is consumed under the lock so concurrent
                # defer() calls share one closure/once-guard
                factory, self._done_factory = self._done_factory, None
                self._server_done = factory()
            return self._server_done

    # ---- result api (mirrors Controller::Failed/ErrorCode/ErrorText) ----

    def failed(self) -> bool:
        return self.error_code != 0

    def set_failed(self, code: int, text: str = "") -> None:
        self.error_code = code
        self.error_text = text or errors.describe(code)

    def set_failed_if_current(self, attempt: int, code: int,
                              text: str = "") -> bool:
        """set_failed iff the call is not completed AND `attempt` is
        still the current attempt — check and set atomically under the
        completion lock, so a stale failure path (a failed write racing
        a concurrently-completing response) can never overwrite a
        finished call's state.  Same discipline as reset_for_retry."""
        with self._lock:
            if self._completed or self.current_attempt != attempt:
                return False
            self.error_code = code
            self.error_text = text or errors.describe(code)
            return True

    def claim_retry(self, owner_attempt: int) -> bool:
        """Atomically claim ownership of the NEXT attempt: succeeds iff
        the call is not completed and `owner_attempt` is still current.
        The winner bumps current_attempt and clears the failed
        attempt's state (the reset_for_retry discipline) in the same
        critical section.  Two failure paths racing to retry the same
        attempt — the writer's failed-write path and the transport's
        failed-socket callback — resolve here to exactly ONE retry
        chain: the loser sees a stale attempt and stands down instead
        of issuing a duplicate attempt (or burning the retry budget
        twice and failing a call whose live attempt was about to
        succeed)."""
        with self._lock:
            if self._completed or self.current_attempt != owner_attempt:
                return False
            self.current_attempt += 1
            self.retried_count += 1
            self.error_code = 0
            self.error_text = ""
            self.response_user_fields = {}
            return True

    def claim_backup(self) -> bool:
        """Atomically take the next attempt number for a backup request
        (no error-state reset — the primary attempt stays live and the
        first response wins).  An unlocked += here would let a backup
        and a concurrent retry claim share one version number, and the
        stale-failure gates built on current_attempt stop gating."""
        with self._lock:
            if self._completed:
                return False
            self.current_attempt += 1
            self.retried_count += 1
            return True

    def reset_for_retry(self) -> None:
        # Guarded by the completion lock: a retry path that loses the
        # race to a concurrently-arriving completion (success response on
        # the dispatcher thread vs the failed-write retry on the caller
        # thread) must NOT wipe the finished call's error/response state
        # — the chaos suite's exactly-once invariant (the doomed extra
        # attempt it goes on to issue is dropped by the pending-table
        # lookup like any stale attempt).
        with self._lock:
            if self._completed:
                return
            self.error_code = 0
            self.error_text = ""
            # fields from a FAILED attempt must not leak into a later
            # successful completion
            self.response_user_fields = {}

    # ---- completion (exactly once) ----

    def _try_complete(self) -> bool:
        """Returns True for the winner; stale attempts/timeouts lose."""
        with self._lock:
            if self._completed:
                return False
            self._completed = True
            return True

    @property
    def completed(self) -> bool:
        return self._completed

    def join(self, extra_timeout_s: float = 5.0) -> None:
        """Block until the RPC completes (sync calls).  With timeout_ms=0
        (deadline disabled) this waits indefinitely."""
        if self._done_event is None:
            return
        if not self.timeout_ms or self.timeout_ms <= 0:
            self._done_event.wait()
            return
        # when the call was issued without a native deadline timer (sync
        # fast path), this thread enforces the deadline exactly — measured
        # from ISSUE time, not join time; otherwise leave slack for the
        # timer to fire first
        if getattr(self, "_sync_deadline", False):
            elapsed = time.monotonic() - self._start_us / 1e6
            budget = max(0.0, self.timeout_ms / 1e3 - elapsed)
        else:
            budget = self.timeout_ms / 1e3 + extra_timeout_s
        if not self._done_event.wait(budget):
            # The deadline timer should have fired; complete the call
            # properly (exactly-once, unregisters) instead of mutating a
            # still-pending controller.
            from brpc_tpu.rpc.channel import CallManager
            CallManager.instance().on_deadline(self.correlation_id)
            self._done_event.wait(1.0)

    def cancel(self) -> bool:
        """StartCancel analog (reference controller.h StartCancel /
        example/cancel_c++): fail this in-flight call with ECANCELED now;
        a late server response is dropped as a stale attempt."""
        from brpc_tpu.rpc.channel import CallManager
        return CallManager.instance().cancel(self.correlation_id)

    def raise_if_failed(self) -> None:
        if self.failed():
            raise errors.RpcError(self.error_code, self.error_text)
