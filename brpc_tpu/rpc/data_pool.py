"""SimpleDataPool — pooled per-request session data
(reference simple_data_pool.{h,cpp} + data_factory.h; the session_data
example).  A server configured with session_data_factory hands every
request controller a pooled object via cntl.session_data; the object is
returned to the pool (after an optional reset) when the request ends, so
expensive per-session state (buffers, caches, device handles) is reused
instead of reallocated.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Optional


class DataFactory:
    """Override create/destroy/reset, or pass plain callables to
    SimpleDataPool directly (data_factory.h analog)."""

    def create(self) -> Any:
        raise NotImplementedError

    def destroy(self, obj: Any) -> None:
        pass

    def reset(self, obj: Any) -> None:
        pass


class _CallableFactory(DataFactory):
    def __init__(self, create: Callable[[], Any],
                 reset: Optional[Callable[[Any], None]] = None):
        self._create = create
        self._reset = reset

    def create(self) -> Any:
        return self._create()

    def reset(self, obj: Any) -> None:
        if self._reset is not None:
            self._reset(obj)


class SimpleDataPool:
    def __init__(self, factory: DataFactory | Callable[[], Any],
                 reset: Optional[Callable[[Any], None]] = None,
                 max_size: int = 1024):
        if not isinstance(factory, DataFactory):
            factory = _CallableFactory(factory, reset)
        self._factory = factory
        self._free: list[Any] = []
        self._mu = threading.Lock()
        self._max_size = max_size
        self._ncreated = 0

    def borrow(self) -> Any:
        with self._mu:
            if self._free:
                return self._free.pop()
            self._ncreated += 1
        return self._factory.create()

    def give_back(self, obj: Any) -> None:
        if obj is None:
            return
        try:
            self._factory.reset(obj)
        except Exception:
            self._factory.destroy(obj)
            return
        with self._mu:
            if len(self._free) < self._max_size:
                self._free.append(obj)
                return
        self._factory.destroy(obj)

    @property
    def stats(self) -> dict:
        with self._mu:
            return {"created": self._ncreated, "free": len(self._free)}
