"""HTTP/2 + gRPC protocol — frame state machine, flow control, unary gRPC
client and server dispatch on the shared port.

Reference: policy/http2_rpc_protocol.cpp (1,840 LoC), details/hpack.cpp
(→ brpc_tpu/rpc/hpack.py), grpc.cpp (status mapping).  The native core
delivers complete h2 frames as MSG_H2 — possibly SEVERAL frames
COALESCED per delivery (meta = the 9-byte headers concatenated, body =
payloads in order; consumers must walk them via feed_frames, never pass
the delivery straight to on_frame) — and auto-detects the client preface
on the shared port, so any real gRPC client that connects to an rpc
Server's port lands here.

Scope: full connection management (SETTINGS/PING/GOAWAY/RST_STREAM/
WINDOW_UPDATE, HEADERS+CONTINUATION assembly, PADDED/PRIORITY flags) and
unary gRPC calls (the reference's gRPC support is unary pb over h2).
Flow control: both directions, credit-based per RFC 7540 §5.2 — the same
producer/consumer windowing the reference uses for StreamWrite (SURVEY §5.7).
"""
from __future__ import annotations

import logging
import queue
import struct
import threading
import time
from concurrent.futures import Future
from typing import Callable, Optional

# sentinel closing a client-side streaming sink (trailers seen, status 0)
_STREAM_END = object()

from brpc_tpu import errors, fault
from brpc_tpu.rpc.hpack import HpackDecoder, HpackEncoder
from brpc_tpu.rpc.transport import MSG_H2, Transport

# frame types (RFC 7540 §6)
DATA, HEADERS, PRIORITY, RST_STREAM, SETTINGS, PUSH_PROMISE, PING, GOAWAY, \
    WINDOW_UPDATE, CONTINUATION = range(10)

FLAG_END_STREAM = 0x1
FLAG_ACK = 0x1
FLAG_END_HEADERS = 0x4
FLAG_PADDED = 0x8
FLAG_PRIORITY = 0x20

SETTINGS_HEADER_TABLE_SIZE = 0x1
SETTINGS_ENABLE_PUSH = 0x2
SETTINGS_MAX_CONCURRENT_STREAMS = 0x3
SETTINGS_INITIAL_WINDOW_SIZE = 0x4
SETTINGS_MAX_FRAME_SIZE = 0x5
SETTINGS_MAX_HEADER_LIST_SIZE = 0x6

DEFAULT_WINDOW = 65535
OUR_WINDOW = 1 << 20          # per-stream window we advertise
OUR_CONN_WINDOW = 64 << 20    # connection window we grow to
OUR_MAX_FRAME = 1 << 20
# assembled header-block cap (SETTINGS_MAX_HEADER_LIST_SIZE analog): a
# CONTINUATION storm must not grow one stream's block without bound
MAX_HEADER_BLOCK = 1 << 20
# per-call bound on rx messages parked ahead of a bidi handler, and on
# raw bytes buffered for a client-streaming call before END: window
# credit is granted on PARSE (both planes), so these caps are the only
# thing between a slow/never-consuming handler and unbounded memory
MAX_BUFFERED_BIDI_MSGS = 1024
MAX_CLIENT_STREAM_RX_BYTES = 64 << 20
# shed events on /vars (both gRPC planes increment this)
from brpc_tpu.bvar import Adder as _Adder  # noqa: E402
grpc_backlog_sheds = _Adder("grpc_rx_backlog_sheds")

H2_PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"

# h2 error codes (RFC 7540 §7)
H2_NO_ERROR, H2_PROTOCOL_ERROR, H2_INTERNAL_ERROR, H2_FLOW_CONTROL_ERROR = \
    0, 1, 2, 3
H2_FRAME_SIZE_ERROR = 6

# gRPC status codes (grpc.cpp's ErrorCodeToGrpcStatus analog)
GRPC_OK = 0
GRPC_UNKNOWN = 2
GRPC_DEADLINE_EXCEEDED = 4
GRPC_NOT_FOUND = 5
GRPC_PERMISSION_DENIED = 7
GRPC_RESOURCE_EXHAUSTED = 8
GRPC_UNIMPLEMENTED = 12
GRPC_INTERNAL = 13
GRPC_UNAVAILABLE = 14
GRPC_UNAUTHENTICATED = 16

_ERR_TO_GRPC = {
    0: GRPC_OK,
    errors.ENOSERVICE: GRPC_UNIMPLEMENTED,
    errors.ENOMETHOD: GRPC_UNIMPLEMENTED,
    errors.ERPCTIMEDOUT: GRPC_DEADLINE_EXCEEDED,
    errors.ELIMIT: GRPC_RESOURCE_EXHAUSTED,
    errors.ELOGOFF: GRPC_UNAVAILABLE,
    errors.ERPCAUTH: GRPC_UNAUTHENTICATED,
    errors.EREJECT: GRPC_PERMISSION_DENIED,
    errors.EINTERNAL: GRPC_INTERNAL,
}
_GRPC_TO_ERR = {
    GRPC_OK: 0,
    GRPC_UNIMPLEMENTED: errors.ENOMETHOD,
    GRPC_DEADLINE_EXCEEDED: errors.ERPCTIMEDOUT,
    GRPC_RESOURCE_EXHAUSTED: errors.ELIMIT,
    GRPC_UNAVAILABLE: errors.ELOGOFF,
    GRPC_UNAUTHENTICATED: errors.ERPCAUTH,
    GRPC_PERMISSION_DENIED: errors.EREJECT,
    GRPC_INTERNAL: errors.EINTERNAL,
}


def err_to_grpc(code: int) -> int:
    return _ERR_TO_GRPC.get(code, GRPC_UNKNOWN)


def grpc_to_err(status: int) -> int:
    return _GRPC_TO_ERR.get(status, errors.EINTERNAL)


def build_frame(ftype: int, flags: int, stream_id: int, payload: bytes) -> bytes:
    n = len(payload)
    hdr = bytes([(n >> 16) & 0xFF, (n >> 8) & 0xFF, n & 0xFF, ftype, flags]) \
        + struct.pack(">I", stream_id & 0x7FFFFFFF)
    return hdr + payload

def feed_frames(conn, meta: bytes, body: bytes) -> None:
    """Deliver one or MORE h2 frames to `conn.on_frame`.  The native
    drain coalesces consecutive frames into one FIFO task (meta = the
    9-byte headers concatenated, body = payloads in order; the payload
    length is the first 3 bytes of each header)."""
    if len(meta) == 9:
        conn.on_frame(meta, body)
        return
    mp = 0
    bp = 0
    n = len(meta)
    while mp + 9 <= n:
        hdr9 = meta[mp:mp + 9]
        ln = (hdr9[0] << 16) | (hdr9[1] << 8) | hdr9[2]
        conn.on_frame(hdr9, body[bp:bp + ln])
        mp += 9
        bp += ln



# ---- per-message compression (grpc.cpp grpc-encoding negotiation) ----
#
# Standard codecs only (gzip + deflate); names travel on the wire in
# grpc-encoding / grpc-accept-encoding.  A message whose compressed flag
# is set decompresses with the STREAM's negotiated codec; a set flag with
# no negotiated codec is the spec's "compressed-flag without grpc-encoding"
# protocol error.

import gzip as _gzip
import zlib as _zlib

# Ceiling on ONE message's decompressed size: tiny compressed frames can
# expand ~1000:1 (decompression bomb); anything bigger than this is
# rejected as corrupt instead of materialized (grpc max-receive-size
# analog).
GRPC_MAX_DECOMPRESSED = 64 << 20


def _bounded_inflate(wbits: int, data: bytes) -> bytes:
    """zlib-family decompress capped at GRPC_MAX_DECOMPRESSED — never
    materializes more than the cap no matter the claimed expansion.
    Loops over members: a gzip body may legally concatenate several
    (RFC 1952), and stopping at the first would silently truncate."""
    budget = GRPC_MAX_DECOMPRESSED
    out = []
    remaining = data
    while True:
        d = _zlib.decompressobj(wbits)
        chunk = d.decompress(remaining, budget + 1)
        if len(chunk) > budget or d.unconsumed_tail:
            raise ValueError("decompressed grpc message exceeds limit")
        if not d.eof:
            raise ValueError("truncated compressed grpc message")
        out.append(chunk)
        budget -= len(chunk)
        remaining = d.unused_data
        if not remaining:
            return b"".join(out)


_GRPC_CODECS: dict[str, tuple[Callable[[bytes], bytes],
                              Callable[[bytes], bytes]]] = {
    "gzip": (lambda b: _gzip.compress(b, 6),
             lambda b: _bounded_inflate(16 + _zlib.MAX_WBITS, b)),
    "deflate": (_zlib.compress,
                lambda b: _bounded_inflate(_zlib.MAX_WBITS, b)),
}
GRPC_ACCEPT_ENCODING = "identity," + ",".join(_GRPC_CODECS)


def grpc_codec(name: Optional[str]):
    """grpc-encoding header value -> (compress, decompress) or None for
    identity.  Raises NotImplementedError on an unknown codec (mapped to
    UNIMPLEMENTED at the call sites, per the gRPC compression spec)."""
    if not name or name == "identity":
        return None
    codec = _GRPC_CODECS.get(name)
    if codec is None:
        raise NotImplementedError(f"unsupported grpc-encoding {name!r}")
    return codec


def negotiated_codec(headers: dict) -> Optional[tuple]:
    """Codec for a peer's DATA per its grpc-encoding header."""
    return grpc_codec(headers.get("grpc-encoding"))


def grpc_frame(payload: bytes, codec: Optional[tuple] = None) -> bytes:
    """5-byte gRPC length prefix (grpc wire format).  With a codec the
    message ships compressed (flag byte 1) — used only after the
    corresponding grpc-encoding header went out."""
    flag = 0
    if codec is not None:
        payload = codec[0](payload)
        flag = 1
    return bytes([flag]) + struct.pack(">I", len(payload)) + payload


def _inflate(flag: int, payload: bytes, codec: Optional[tuple]) -> bytes:
    """Apply the stream codec to one popped message body."""
    if flag == 0:
        return payload
    return codec[1](payload)


def pop_grpc_frames(data: bytearray, codec: Optional[tuple] = None
                    ) -> tuple[list[bytes], Optional[str]]:
    """Pop every COMPLETE length-prefixed message off the front of a
    stream buffer (in place).  Returns (messages, error): error is set on
    a bad flag byte or a compressed message without a negotiated codec —
    ONE implementation for the client sink drain and the server bidi
    feed."""
    msgs: list[bytes] = []
    off = 0
    err: Optional[str] = None
    while len(data) - off >= 5:
        flag = data[off]
        (ln,) = struct.unpack_from(">I", data, off + 1)
        if flag > 1 or (flag == 1 and codec is None):
            err = ("compressed grpc message without grpc-encoding"
                   if flag == 1 else "bad grpc frame flag")
            break
        if len(data) - off - 5 < ln:
            break
        try:
            msgs.append(_inflate(flag, bytes(data[off + 5:off + 5 + ln]),
                                 codec))
        except ValueError as e:   # oversized expansion keeps its message
            err = str(e)
            break
        except Exception:
            err = "corrupt compressed grpc message"
            break
        off += 5 + ln
    if off:
        del data[:off]
    return msgs, err


def parse_grpc_frames(data: bytes, codec: Optional[tuple] = None
                      ) -> list[bytes]:
    out = []
    pos = 0
    while pos + 5 <= len(data):
        flag = data[pos]
        if flag > 1 or (flag == 1 and codec is None):
            # compressed flag set without a negotiated grpc-encoding —
            # the spec mandates UNIMPLEMENTED, not silent passthrough
            raise NotImplementedError(
                "compressed grpc message without grpc-encoding"
                if flag == 1 else "bad grpc frame flag")
        n = struct.unpack(">I", data[pos + 1:pos + 5])[0]
        if pos + 5 + n > len(data):
            raise ValueError("truncated grpc frame")
        try:
            out.append(_inflate(flag, data[pos + 5:pos + 5 + n], codec))
        except (NotImplementedError, ValueError):
            raise              # oversized expansion keeps its message
        except Exception:
            raise ValueError("corrupt compressed grpc message")
        pos += 5 + n
    if pos != len(data):
        raise ValueError("trailing bytes after grpc frame")
    return out


_CODEC_UNSET = ("unset",)


class _StreamState:
    __slots__ = ("id", "headers", "data", "trailers", "ended", "send_window",
                 "header_block", "expect_continuation", "trailer_phase",
                 "reset", "rx_codec", "recv_unacked", "responded")

    def __init__(self, sid: int, initial_window: int):
        self.id = sid
        self.headers: list[tuple[str, str]] = []
        self.data = bytearray()
        self.trailers: list[tuple[str, str]] = []
        self.ended = False
        self.send_window = initial_window
        self.header_block = bytearray()
        self.expect_continuation = False
        self.trailer_phase = False
        self.reset = False
        # server side: set atomically under _fc by the responder that
        # claims this stream's response HEADERS (claim_responder) — the
        # duplicate-trailers guard for shed-vs-handler races
        self.responded = False
        # peer's grpc-encoding codec, resolved once at HEADERS time
        # (deriving it per DATA frame is O(headers) on the hot path)
        self.rx_codec = _CODEC_UNSET
        # received-but-unacked bytes (coalesced stream WINDOW_UPDATEs)
        self.recv_unacked = 0


class H2Connection:
    """One side of an h2 connection over a native socket.

    Subclasses implement on_request_complete (server) / on_response (client).
    All frame handling runs on the native dispatcher thread for this socket;
    sends are serialized by _send_lock.
    """

    def __init__(self, sock_id: Optional[int], is_server: bool):
        # sock_id may be None for clients that bind after connect() returns
        # (the socket id also arrives with every message callback)
        self.sid = sock_id
        self.is_server = is_server
        self._tp = Transport.instance()
        self._enc = HpackEncoder()
        self._dec = HpackDecoder()
        self._send_lock = threading.Lock()
        self._fc = threading.Condition(threading.Lock())
        self.remote_conn_window = DEFAULT_WINDOW
        self.remote_initial_window = DEFAULT_WINDOW
        self.remote_max_frame = 16384
        self._recv_conn_consumed = 0
        self._streams: dict[int, _StreamState] = {}
        self._sent_settings = False
        self._goaway = False
        # fatal local condition (oversized/undecodable header block):
        # the HPACK dynamic table may be desynced, so NO further frame
        # may be decoded on this connection (RFC 7540 §4.3 connection
        # error semantics)
        self._fatal = False
        self._cont_stream: Optional[int] = None  # stream awaiting CONTINUATION

    # ---- send side ----

    # advertised SETTINGS_MAX_CONCURRENT_STREAMS — deliberately high:
    # capping it would throttle compliant clients' UNARY concurrency,
    # which we don't bound per-stream.  The server's enforced bound on
    # streaming calls is the separate GrpcServerConnection
    # .max_streaming_calls, backed by grpc-status 8.
    max_concurrent_streams = 1 << 20

    def send_preface_and_settings(self) -> None:
        settings = struct.pack(">HI", SETTINGS_INITIAL_WINDOW_SIZE, OUR_WINDOW) \
            + struct.pack(">HI", SETTINGS_MAX_FRAME_SIZE, OUR_MAX_FRAME) \
            + struct.pack(">HI", SETTINGS_MAX_CONCURRENT_STREAMS,
                          self.max_concurrent_streams)
        wu = struct.pack(">I", OUR_CONN_WINDOW - DEFAULT_WINDOW)
        first = b"" if self.is_server else H2_PREFACE
        with self._send_lock:
            if self._sent_settings:
                return
            self._sent_settings = True
            self._tp.write_raw(
                self.sid,
                first + build_frame(SETTINGS, 0, 0, settings)
                + build_frame(WINDOW_UPDATE, 0, 0, wu))

    def _chaos_frames(self, data: bytes) -> Optional[bytes]:
        """h2.send fault interpretation, shared by _send AND the joined
        unary fast paths (which write_raw directly): returns the bytes
        to put on the wire — mangled by a CORRUPT fault — or None for an
        injected send failure (a counted injection is never a no-op).
        On None the CALLER must invoke _chaos_kill OUTSIDE _send_lock:
        failure callbacks fire synchronously and may send (GOAWAY), so
        closing under the non-reentrant send lock would self-deadlock."""
        f = fault.hit("h2.send", sid=self.sid)
        if f is None:
            return data
        if f.kind == fault.CORRUPT:
            # one flipped byte: the peer's framing/HPACK checks must
            # catch it (protocol error -> fatal/GOAWAY), or it surfaces
            # as a corrupted grpc message body
            return fault.mangle(data)
        return None

    def _chaos_kill(self) -> None:
        """Injected send failure: the connection dies the way a real
        mid-write failure kills it."""
        if self.sid is not None:
            self._tp.close(self.sid)

    def _send(self, data: bytes) -> None:
        if fault.ENABLED:
            data = self._chaos_frames(data)
            if data is None:
                self._chaos_kill()
                return
        with self._send_lock:
            self._tp.write_raw(self.sid, data)

    def send_headers(self, stream_id: int, headers: list[tuple[str, str]],
                     end_stream: bool = False) -> None:
        # HPACK encoder state must advance in the exact order blocks hit the
        # wire, so encode under the send lock
        with self._send_lock:
            block = self._enc.encode_cached(tuple(headers))
            flags = FLAG_END_HEADERS | (FLAG_END_STREAM if end_stream else 0)
            self._tp.write_raw(self.sid,
                               build_frame(HEADERS, flags, stream_id, block))

    def open_stream(self, stream_id: int) -> _StreamState:
        with self._fc:
            st = self._streams.get(stream_id)
            if st is None:
                st = _StreamState(stream_id, self.remote_initial_window)
                self._streams[stream_id] = st
            return st

    def close_stream(self, stream_id: int) -> None:
        with self._fc:
            self._streams.pop(stream_id, None)

    def claim_responder(self, stream_id: int) -> bool:
        """Atomically claim the right to open the response on
        `stream_id` (ADVICE r5).  The liveness check and the claim
        happen under ONE _fc hold, so a backlog shed and a concurrently
        finishing handler can never BOTH emit response/trailers HEADERS
        on the same stream — the old check-then-act guard released _fc
        before send_headers, leaving that window open.  Returns False
        when the stream is gone (shed/RST/closed) or another responder
        already won; the loser stays silent."""
        with self._fc:
            st = self._streams.get(stream_id)
            if st is None or st.responded:
                return False
            st.responded = True
            return True

    def send_data(self, stream_id: int, data: bytes,
                  end_stream: bool = True, timeout_s: float = 30.0) -> None:
        """Chunked, flow-controlled DATA send (blocks on zero window —
        the StreamWrite credit-wait analog, stream.cpp:274-290).  Must NOT
        be called from the dispatcher thread that feeds on_frame for this
        socket: the WINDOW_UPDATE that unblocks it arrives there."""
        pos = 0
        deadline = time.monotonic() + timeout_s
        while True:
            with self._fc:
                while True:
                    st = self._streams.get(stream_id)
                    if st is None or st.reset:
                        raise errors.RpcError(errors.EFAILEDSOCKET,
                                              "h2 stream closed during send")
                    win = min(self.remote_conn_window, st.send_window)
                    if win > 0 or pos >= len(data):
                        break
                    left = deadline - time.monotonic()
                    if left <= 0 or not self._tp.alive(self.sid):
                        raise errors.RpcError(errors.ERPCTIMEDOUT,
                                              "h2 flow control stalled")
                    self._fc.wait(min(left, 1.0))
                n = min(win, self.remote_max_frame, len(data) - pos)
                self.remote_conn_window -= n
                st.send_window -= n
            chunk = data[pos:pos + n]
            pos += n
            last = pos >= len(data)
            self._send(build_frame(
                DATA, FLAG_END_STREAM if (end_stream and last) else 0,
                stream_id, chunk))
            if last:
                return

    def _enter_fatal(self, code: int) -> None:
        """Mark the connection unrecoverable (desynced HPACK / protocol
        violation), GOAWAY the peer, and notify the subclass so in-flight
        work fails NOW instead of by timeout (ADVICE r4: a client conn
        that reported alive() after _fatal kept being reused until the
        peer closed the socket)."""
        self._fatal = True
        try:
            self.send_goaway(code=code)
        except Exception:
            pass
        self.on_fatal()

    def on_fatal(self) -> None:
        """Subclass hook: fail registered calls/sinks, stop advertising
        alive().  Default: close the socket — relying on the peer's
        reaction to GOAWAY would let a peer that ignores it pin the fd,
        stream buffers, and dispatcher registration forever (one
        malformed frame per connection, then hold it open)."""
        if self.sid is not None:
            try:
                self._tp.close(self.sid)
            except Exception:
                pass

    def _claim_window(self, stream_id: int, n: int) -> bool:
        """Atomically claim `n` bytes of conn+stream send window for a
        single-frame body, or return False (caller takes the chunked
        send_data path).  Raises if the stream is gone."""
        with self._fc:
            st = self._streams.get(stream_id)
            if st is None or st.reset:
                raise errors.RpcError(errors.EFAILEDSOCKET,
                                      "h2 stream closed during send")
            if n and (n > self.remote_max_frame or
                      self.remote_conn_window < n or st.send_window < n):
                return False
            self.remote_conn_window -= n
            st.send_window -= n
        return True

    def send_request_joined(self, stream_id: int,
                            headers: list[tuple[str, str]],
                            data: bytes) -> bool:
        """HEADERS + DATA(END_STREAM) in ONE socket write — the unary
        client fast path (each write_raw costs ~40us on a busy host).
        False = window too small now; caller falls back to send_headers
        + send_data."""
        if not self._claim_window(stream_id, len(data)):
            return False
        with self._send_lock:
            buf = build_frame(HEADERS, FLAG_END_HEADERS, stream_id,
                              self._enc.encode_cached(tuple(headers)))
            buf += build_frame(DATA, FLAG_END_STREAM, stream_id, data)
            if fault.ENABLED:
                buf = self._chaos_frames(buf)
            if buf is not None:
                self._tp.write_raw(self.sid, buf)
        if buf is None:
            self._chaos_kill()    # outside _send_lock (callbacks may send)
        return True

    def send_response_joined(self, stream_id: int,
                             headers: list[tuple[str, str]], data: bytes,
                             trailers: list[tuple[str, str]]) -> bool:
        """HEADERS + DATA + trailing HEADERS(END_STREAM) in ONE write —
        the unary server fast path.  Same fallback contract."""
        if not self._claim_window(stream_id, len(data)):
            return False
        with self._send_lock:
            buf = build_frame(HEADERS, FLAG_END_HEADERS, stream_id,
                              self._enc.encode_cached(tuple(headers)))
            buf += build_frame(DATA, 0, stream_id, data)
            buf += build_frame(HEADERS, FLAG_END_HEADERS | FLAG_END_STREAM,
                               stream_id,
                               self._enc.encode_cached(tuple(trailers)))
            if fault.ENABLED:
                buf = self._chaos_frames(buf)
            if buf is not None:
                self._tp.write_raw(self.sid, buf)
        if buf is None:
            self._chaos_kill()    # outside _send_lock (callbacks may send)
        return True

    def send_rst(self, stream_id: int, code: int) -> None:
        self._send(build_frame(RST_STREAM, 0, stream_id,
                               struct.pack(">I", code)))

    def send_goaway(self, last_stream: int = 0,
                    code: int = H2_NO_ERROR) -> None:
        self._send(build_frame(GOAWAY, 0, 0,
                               struct.pack(">II", last_stream, code)))

    # ---- receive side ----

    def on_frame(self, hdr9: bytes, payload: bytes) -> None:
        if self._fatal:
            return      # desynced HPACK state: nothing more is decodable
        if fault.ENABLED:
            f = fault.hit("h2.recv", sid=self.sid)
            if f is not None and f.kind == fault.DROP:
                return  # frame lost above the transport
        ftype = hdr9[3]
        flags = hdr9[4]
        stream_id = struct.unpack(">I", hdr9[5:9])[0] & 0x7FFFFFFF
        if len(payload) > OUR_MAX_FRAME:
            # larger than our advertised SETTINGS_MAX_FRAME_SIZE: a
            # compliant peer never sends this, and an oversized HEADERS
            # would bypass MAX_HEADER_BLOCK 16x (the native parser caps
            # frames at 16MB, not at our advertisement)
            self._enter_fatal(H2_FRAME_SIZE_ERROR)
            return
        if self._cont_stream is not None and ftype != CONTINUATION:
            # RFC 7540 §6.10: interleaving inside a header block is a
            # CONNECTION error — and the partial block's dynamic-table
            # inserts were never applied, so later decodes would desync
            self._enter_fatal(H2_PROTOCOL_ERROR)
            return
        if ftype == SETTINGS:
            self._on_settings(flags, payload)
        elif ftype == WINDOW_UPDATE:
            self._on_window_update(stream_id, payload)
        elif ftype == HEADERS:
            self._on_headers(stream_id, flags, payload)
        elif ftype == CONTINUATION:
            self._on_continuation(stream_id, flags, payload)
        elif ftype == DATA:
            self._on_data(stream_id, flags, payload)
        elif ftype == PING:
            if not (flags & FLAG_ACK):
                self._send(build_frame(PING, FLAG_ACK, 0, payload))
        elif ftype == RST_STREAM:
            with self._fc:
                st = self._streams.pop(stream_id, None)
                if st is not None:
                    st.reset = True
                self._fc.notify_all()
            if st is not None:
                code = struct.unpack(">I", payload[:4])[0] if len(payload) >= 4 \
                    else H2_PROTOCOL_ERROR
                self.on_stream_reset(stream_id, code)
        elif ftype == GOAWAY:
            self._goaway = True
            last = struct.unpack(">I", payload[:4])[0] & 0x7FFFFFFF \
                if len(payload) >= 4 else 0
            self.on_goaway(last)
        # PRIORITY / PUSH_PROMISE ignored (push disabled)

    def _on_settings(self, flags: int, payload: bytes) -> None:
        if flags & FLAG_ACK:
            return
        pos = 0
        while pos + 6 <= len(payload):
            ident, value = struct.unpack(">HI", payload[pos:pos + 6])
            pos += 6
            if ident == SETTINGS_INITIAL_WINDOW_SIZE:
                with self._fc:
                    delta = value - self.remote_initial_window
                    self.remote_initial_window = value
                    for st in self._streams.values():
                        st.send_window += delta
                    self._fc.notify_all()
            elif ident == SETTINGS_MAX_FRAME_SIZE:
                self.remote_max_frame = max(16384, min(value, (1 << 24) - 1))
            elif ident == SETTINGS_HEADER_TABLE_SIZE:
                self._enc.set_max_table_size(min(value, 4096))
        self._send(build_frame(SETTINGS, FLAG_ACK, 0, b""))

    def _on_window_update(self, stream_id: int, payload: bytes) -> None:
        if len(payload) < 4:
            return
        incr = struct.unpack(">I", payload[:4])[0] & 0x7FFFFFFF
        with self._fc:
            if stream_id == 0:
                self.remote_conn_window += incr
            else:
                st = self._streams.get(stream_id)
                if st is not None:
                    st.send_window += incr
            self._fc.notify_all()

    def _strip_padding(self, flags: int, payload: bytes,
                       priority: bool) -> Optional[bytes]:
        """Returns the frame content, or None for a malformed frame (pad
        length >= remaining payload, RFC 7540 §6.1 connection error)."""
        pos = 0
        pad = 0
        if flags & FLAG_PADDED:
            if not payload:
                # §6.1 connection error; for HEADERS the dropped block
                # also desyncs HPACK, so the connection is unrecoverable
                self._enter_fatal(H2_PROTOCOL_ERROR)
                return None
            pad = payload[0]
            pos = 1
        if priority and (flags & FLAG_PRIORITY):
            pos += 5
        end = len(payload) - pad
        if end < pos:
            self._enter_fatal(H2_PROTOCOL_ERROR)
            return None
        return payload[pos:end]

    def _stream(self, stream_id: int) -> _StreamState:
        return self.open_stream(stream_id)

    def _on_headers(self, stream_id: int, flags: int, payload: bytes) -> None:
        if stream_id == 0:
            # §6.2 connection error; the undecoded block's table inserts
            # would desync every later decode
            self._enter_fatal(H2_PROTOCOL_ERROR)
            return
        block = self._strip_padding(flags, payload, priority=True)
        if block is None:
            return
        st = self._stream(stream_id)
        st.header_block = bytearray(block)
        if st.headers:        # second HEADERS on a stream = trailers
            st.trailer_phase = True
        if flags & FLAG_END_STREAM:
            st.ended = True
        if flags & FLAG_END_HEADERS:
            self._finish_header_block(st)
        else:
            self._cont_stream = stream_id

    def _on_continuation(self, stream_id: int, flags: int,
                         payload: bytes) -> None:
        if self._cont_stream != stream_id:
            # CONTINUATION for the wrong stream (or none pending): §6.10
            # connection error, and the pending block (if any) is now
            # unfinishable without desyncing HPACK
            self._enter_fatal(H2_PROTOCOL_ERROR)
            return
        st = self._stream(stream_id)
        st.header_block += payload
        if len(st.header_block) > MAX_HEADER_BLOCK:
            # SETTINGS_MAX_HEADER_LIST_SIZE enforcement: an unbounded
            # CONTINUATION run must not grow memory without limit.
            # FATAL: the discarded block's dynamic-table inserts were
            # never applied, so later blocks would decode wrongly
            st.header_block = bytearray()
            self._cont_stream = None
            self._enter_fatal(H2_PROTOCOL_ERROR)
            return
        if flags & FLAG_END_HEADERS:
            self._cont_stream = None
            self._finish_header_block(st)

    def _finish_header_block(self, st: _StreamState) -> None:
        try:
            headers = self._dec.decode(bytes(st.header_block))
        except ValueError:
            # undecodable block = desynced dynamic table: fatal (§4.3)
            self._enter_fatal(H2_PROTOCOL_ERROR)
            return
        st.header_block = bytearray()
        if st.trailer_phase:
            st.trailers = headers
        else:
            st.headers = headers
            if not st.ended:
                # headers done, request body still open: bidi consumers
                # dispatch HERE instead of waiting for END_STREAM
                self.on_stream_headers(st)
        if st.ended:
            self._complete(st)

    def _on_data(self, stream_id: int, flags: int, payload: bytes) -> None:
        # Replenish the connection window even for unknown/reset streams:
        # in-flight DATA after an RST still consumed connection credit, and
        # dropping it without a WINDOW_UPDATE would leak the window
        # permanently.  (Receiver-side credit return, the CONSUMED-feedback
        # analog of stream_impl.h:80 — we buffer in host RAM, no
        # backpressure needed at this layer.)  COALESCED: the conn-level
        # ack goes out once per OUR_CONN_WINDOW/4 consumed bytes rather
        # than per frame (the peer's window floor stays at 3/4 capacity),
        # and ended streams skip the stream-level ack entirely — per-frame
        # WINDOW_UPDATE writes were one of the top per-call costs of the
        # unary gRPC path.  Frames arrive on this connection's FIFO lane,
        # so the counter is single-threaded.
        if len(payload):
            self._recv_conn_consumed += len(payload)
            frames = b""
            if self._recv_conn_consumed >= OUR_CONN_WINDOW // 4:
                frames += build_frame(
                    WINDOW_UPDATE, 0, 0,
                    struct.pack(">I", self._recv_conn_consumed))
                self._recv_conn_consumed = 0
            if not (flags & FLAG_END_STREAM):
                # stream-level credit, also coalesced: ack at half the
                # advertised window so the peer's floor stays at
                # OUR_WINDOW/2 (unary responses never reach it — their
                # stream dies with the trailers anyway)
                sst = self._streams.get(stream_id)
                if sst is not None:
                    sst.recv_unacked += len(payload)
                    if sst.recv_unacked >= OUR_WINDOW // 2:
                        frames += build_frame(
                            WINDOW_UPDATE, 0, stream_id,
                            struct.pack(">I", sst.recv_unacked))
                        sst.recv_unacked = 0
            if frames:
                self._send(frames)
        st = self._streams.get(stream_id)
        if st is None:
            return
        data = self._strip_padding(flags, payload, priority=False)
        if data is None:
            return
        st.data += data
        if not (flags & FLAG_END_STREAM):
            # incremental delivery hook (server-streaming gRPC consumes
            # complete length-prefixed messages as they arrive)
            self.on_stream_data(st)
        else:
            st.ended = True
            self._complete(st)

    def _complete(self, st: _StreamState) -> None:
        # NOTE: the stream stays in _streams so its send window keeps
        # tracking WINDOW_UPDATEs while the response goes out; the
        # subclass closes it (client: immediately; server: after the
        # response's END_STREAM).
        self.on_stream_complete(st)

    # ---- overridables ----

    def on_stream_headers(self, st: _StreamState) -> None:
        """Called when the request HEADERS block completes on a stream
        whose body is still open (no-op by default; bidi consumers
        dispatch here)."""

    def on_stream_data(self, st: _StreamState) -> None:
        """Called as DATA accumulates on a still-open stream (no-op by
        default; streaming consumers override to drain complete
        messages incrementally)."""

    def on_stream_complete(self, st: _StreamState) -> None:
        raise NotImplementedError

    def on_stream_reset(self, stream_id: int, code: int) -> None:
        pass

    def on_goaway(self, last_stream: int) -> None:
        pass


_GRPC_TIMEOUT_UNITS = {"H": 3600.0, "M": 60.0, "S": 1.0,
                       "m": 1e-3, "u": 1e-6, "n": 1e-9}


# Messages below this ship uncompressed even on a compressing stream
# (per-message flag, gRPC compression spec) — tiny payloads inflate.
GRPC_COMPRESS_MIN = 1024


def grpc_frame_auto(payload: bytes, codec: Optional[tuple]) -> bytes:
    """Length-prefix one message, compressing only when the stream has a
    codec AND the message is big enough to benefit."""
    if codec is not None and len(payload) >= GRPC_COMPRESS_MIN:
        return grpc_frame(payload, codec)
    return grpc_frame(payload)


def response_codec_for(h: dict) -> tuple[Optional[str], Optional[tuple]]:
    """Server response codec: MIRROR the request's encoding (the gRPC
    default — a client that didn't compress gets identity back even
    though it advertises accept-encoding; one that did compress gets its
    own codec, which its accept list necessarily covers)."""
    name = h.get("grpc-encoding")
    if not name or name == "identity" or name not in _GRPC_CODECS:
        return None, None
    accept = h.get("grpc-accept-encoding")
    if accept and name not in {tok.strip() for tok in accept.split(",")}:
        return None, None
    return name, _GRPC_CODECS[name]


def parse_grpc_timeout(value: Optional[str]) -> Optional[float]:
    """grpc-timeout header ("8-digit value + unit", e.g. '5S', '100m')
    → seconds, or None if absent/malformed."""
    if not value or len(value) < 2:
        return None
    unit = _GRPC_TIMEOUT_UNITS.get(value[-1])
    if unit is None or not value[:-1].isdigit():
        return None
    return int(value[:-1]) * unit


_grpc_pool = None
_grpc_pool_lock = threading.Lock()


class _LeanPool:
    """Fire-and-forget worker pool: SimpleQueue + fixed threads.  No
    callers consume the Future, so ThreadPoolExecutor's per-submit
    machinery (Future allocation, idle-semaphore bookkeeping,
    _adjust_thread_count's lock dance) is pure overhead — profiled at
    ~1/3 of the whole gRPC bridge dispatch cost under the native pump.
    SimpleQueue.put/get are C-level and lock-free for this pattern."""

    def __init__(self, workers: int, name: str):
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        for i in range(workers):
            # daemon by design: the pool is fire-and-forget dispatch, and
            # graceful shutdown is owned a level up (Server.stop/join
            # drains in-flight calls through the inflight accounting);
            # every other worker thread in this codebase is daemon too
            threading.Thread(target=self._run, daemon=True,
                             name=f"{name}-{i}").start()

    def _run(self) -> None:
        get = self._q.get
        while True:
            item = get()
            fn, args = item
            try:
                fn(*args)
            # BaseException: a handler calling sys.exit() must not
            # permanently shrink the pool (a dead worker is never
            # replaced; 32 of them and every later request hangs)
            except BaseException:  # pragma: no cover - handler bug guard
                logging.exception("grpc worker task failed")
            # drop the task before parking in get(), or an idle worker
            # pins the last request's payload until the next dispatch
            # (the ThreadPoolExecutor `del work_item` discipline)
            item = fn = args = None

    def submit(self, fn, *args) -> None:
        self._q.put((fn, args))


def _grpc_executor():
    """Shared worker pool for server-side gRPC dispatch.  The h2 frame
    machinery runs FIFO on the dispatcher thread (HPACK state demands it);
    user handlers + flow-controlled response sends must hop off it —
    send_data blocks on WINDOW_UPDATEs the dispatcher delivers (the
    usercode_in_pthread backup-pool pattern, SURVEY §5.10)."""
    global _grpc_pool
    with _grpc_pool_lock:
        if _grpc_pool is None:
            _grpc_pool = _LeanPool(32, "grpc-worker")
        return _grpc_pool


class GrpcServerConnection(H2Connection):
    """Server side of one h2 connection; dispatches unary gRPC requests
    into the Server's method registry (same gates as native-protocol
    traffic — see Server.invoke_grpc)."""

    # the enforced bound on concurrently-SERVED streaming calls per
    # connection (each holds 1-2 dedicated rx/tx threads); unary dispatch
    # rides the bounded shared pool and is NOT slot-gated, so the
    # SETTINGS advertisement stays high (capping it would throttle
    # compliant clients' unary concurrency) — excess streaming calls get
    # grpc-status 8 instead.
    max_streaming_calls = 128

    def __init__(self, sock_id: int, server):
        super().__init__(sock_id, is_server=True)
        self._server = server
        # bidi request feeds: stream id -> (queue, request codec)
        self._bidi_rx: dict[int, tuple["queue.Queue", Optional[tuple]]] = {}
        self._bidi_lock = threading.Lock()
        self._stream_slots: set[int] = set()   # streams holding a slot
        self.send_preface_and_settings()

    # ---- streaming budget, one slot PER STREAM (a HEADERS frame is
    # cheap for the peer; an unbounded thread per stream is not —
    # advisor r3 #2).  A stream's rx AND tx threads share its slot. ----

    def _acquire_stream_slot(self, stream_id: int) -> bool:
        with self._bidi_lock:
            if stream_id in self._stream_slots:
                return True
            if len(self._stream_slots) >= self.max_streaming_calls:
                return False
            self._stream_slots.add(stream_id)
            return True

    def _release_stream_slot(self, stream_id: int) -> None:
        with self._bidi_lock:
            self._stream_slots.discard(stream_id)

    # ---- BIDI: dispatch at headers, feed request frames as they arrive --

    def on_stream_headers(self, st: _StreamState) -> None:
        h = dict(st.headers)
        if h.get("grpc-bidi") != "1":
            return                      # unary/client-stream: wait for end
        try:
            codec = negotiated_codec(h)
        except NotImplementedError as e:
            self._respond_error(st.id, GRPC_UNIMPLEMENTED, str(e))
            self.close_stream(st.id)
            return
        if not self._acquire_stream_slot(st.id):
            self._respond_error(st.id, GRPC_RESOURCE_EXHAUSTED,
                                "too many concurrent streams")
            self.close_stream(st.id)
            return
        rx: "queue.Queue" = queue.Queue()
        with self._bidi_lock:
            self._bidi_rx[st.id] = (rx, codec)
        # dedicated thread: a bidi handler legitimately blocks waiting
        # for its peer's next message — that must not park one of the
        # bounded shared grpc workers for the call's lifetime
        threading.Thread(target=self._process_bidi, args=(st, rx),
                         daemon=True,
                         name=f"grpc-bidi-rx-{st.id}").start()

    def on_stream_data(self, st: _StreamState) -> None:
        with self._bidi_lock:
            entry = self._bidi_rx.get(st.id)
        if entry is None:
            # non-bidi stream accumulating toward END (client-streaming
            # collect, or a unary body): window credit was granted on
            # receipt, so cap the buffered bytes — the native plane's
            # kMaxGrpcMessage discipline
            if len(st.data) > MAX_CLIENT_STREAM_RX_BYTES:
                grpc_backlog_sheds.add(1)
                del st.data[:]
                self._respond_error(st.id, GRPC_RESOURCE_EXHAUSTED,
                                    "request stream backlog exceeded")
                self.send_rst(st.id, 0x8)    # CANCEL
                self.close_stream(st.id)
            return
        rx, codec = entry
        msgs, err = pop_grpc_frames(st.data, codec)
        for m in msgs:
            if rx.qsize() >= MAX_BUFFERED_BIDI_MSGS:
                grpc_backlog_sheds.add(1)
                rx.put(errors.RpcError(
                    errors.ELIMIT, "bidi rx backlog exceeded"))
                with self._bidi_lock:
                    self._bidi_rx.pop(st.id, None)
                del st.data[:]
                self._respond_error(st.id, GRPC_RESOURCE_EXHAUSTED,
                                    "bidi rx backlog exceeded")
                # RST too: a flooder ignoring the trailers would otherwise
                # keep burning receive bandwidth on the dead stream (the
                # framing-error branch below does the same)
                self.send_rst(st.id, 0x8)    # CANCEL
                self.close_stream(st.id)
                return
            rx.put(m)
        if err is not None:
            # framing is unrecoverable: error the handler ONCE, stop
            # feeding (pop the entry so later DATA can't re-queue), drop
            # the garbage, RST so the peer stops sending, and CLOSE the
            # stream so an in-flight END_STREAM can't re-dispatch it
            rx.put(errors.RpcError(errors.EREQUEST, err))
            with self._bidi_lock:
                self._bidi_rx.pop(st.id, None)
            del st.data[:]
            self.send_rst(st.id, 0x1)    # PROTOCOL_ERROR
            self.close_stream(st.id)

    def on_stream_complete(self, st: _StreamState) -> None:
        with self._bidi_lock:
            entry = self._bidi_rx.get(st.id)
        if entry is not None:
            self.on_stream_data(st)     # tail frames
            entry[0].put(_STREAM_END)   # half-close: request side done
            with self._bidi_lock:       # feeding is over; drop the entry
                self._bidi_rx.pop(st.id, None)
            return                      # handler already running
        if any(k == "grpc-bidi" and v == "1" for k, v in st.headers):
            # bidi stream whose feed entry is already gone: the call was
            # served (tx finished before the client's half-close arrived)
            # — dispatching _process here would invoke the handler a
            # SECOND time on an empty payload (race vs _transmit_stream's
            # cleanup)
            return
        # runs on the dispatcher thread: only parse + hand off
        _grpc_executor().submit(self._process, st)

    def on_stream_reset(self, stream_id: int, code: int) -> None:
        with self._bidi_lock:
            entry = self._bidi_rx.pop(stream_id, None)
        if entry is not None:
            entry[0].put(errors.RpcError(errors.ECANCELED,
                                         f"stream reset (h2 error {code})"))

    def abort_bidi(self) -> None:
        """Connection died: unblock every parked bidi handler — a
        request_iter waiting in rx.get() would otherwise hang forever,
        leaking the inflight slot and wedging graceful join()."""
        with self._bidi_lock:
            entries, self._bidi_rx = dict(self._bidi_rx), {}
        for rx, _codec in entries.values():
            rx.put(errors.RpcError(errors.ECANCELED,
                                   "h2 connection lost"))

    def _process_bidi(self, st: _StreamState, rx: "queue.Queue") -> None:
        """BIDI: the handler runs while the request side is still open,
        consuming an iterator of request messages and returning an
        iterator of responses; transmission rides the same dedicated
        thread as server-streaming."""
        resp = None
        handed_off = False
        try:
            h = dict(st.headers)
            parts = h.get(":path", "").strip("/").split("/")
            if len(parts) != 2:
                self._respond_error(st.id, GRPC_UNIMPLEMENTED, "bad path")
                return

            # honor a client-supplied deadline for the whole call: the
            # request iterator stops waiting once it passes
            timeout_s = parse_grpc_timeout(h.get("grpc-timeout"))
            deadline = (time.monotonic() + timeout_s) if timeout_s else None

            def request_iter():
                while True:
                    if deadline is None:
                        item = rx.get()
                    else:
                        left = deadline - time.monotonic()
                        if left <= 0:
                            raise errors.RpcError(
                                errors.ERPCTIMEDOUT,
                                "bidi deadline exceeded on server")
                        try:
                            item = rx.get(timeout=left)
                        except queue.Empty:
                            raise errors.RpcError(
                                errors.ERPCTIMEDOUT,
                                "bidi deadline exceeded on server")
                    if item is _STREAM_END:
                        return
                    if isinstance(item, Exception):
                        raise item
                    yield item

            resp, code, text = self._server.invoke_grpc(
                parts[0], parts[1], b"", h, peer_sid=self.sid,
                payload_iter=request_iter())
            if code != 0:
                self._respond_error(st.id, err_to_grpc(code), text)
                return
            if not self.claim_responder(st.id):
                return   # shed/reset while the handler ran: stay silent
            enc_name, tx_codec = response_codec_for(h)
            self.send_headers(st.id, self._resp_headers(enc_name))
            if isinstance(resp, (bytes, bytearray, memoryview)):
                self.send_data(st.id, grpc_frame_auto(bytes(resp), tx_codec),
                               end_stream=False)
                self.send_headers(st.id, [("grpc-status", "0")],
                                  end_stream=True)
            else:
                body, resp = resp, None
                handed_off = True   # the tx thread inherits this
                threading.Thread(target=self._transmit_stream,
                                 args=(st, body, tx_codec), daemon=True,
                                 name=f"grpc-bidi-tx-{st.id}").start()
        except errors.RpcError:
            pass
        except Exception:  # pragma: no cover - handler bug guard
            import traceback
            traceback.print_exc()
        finally:
            if not handed_off:
                with self._bidi_lock:
                    self._bidi_rx.pop(st.id, None)
                if hasattr(resp, "close"):
                    try:
                        resp.close()
                    except Exception:
                        pass
                self._release_stream_slot(st.id)
                self.close_stream(st.id)

    def _process(self, st: _StreamState) -> None:
        resp = None
        handed_off = False
        try:
            h = dict(st.headers)
            path = h.get(":path", "")
            try:
                msgs = parse_grpc_frames(bytes(st.data), negotiated_codec(h))
                # the request header — not frame counting — decides the
                # handler contract: a marked client-stream delivers the
                # full message LIST (even with 0 or 1 messages); an
                # unmarked multi-frame body still delivers the list so
                # messages are never silently dropped
                if h.get("grpc-client-streaming") == "1" or len(msgs) > 1:
                    payload = msgs
                else:
                    payload = msgs[0] if msgs else b""
            except NotImplementedError as e:
                self._respond_error(st.id, GRPC_UNIMPLEMENTED, str(e))
                return
            except ValueError:
                self._respond_error(st.id, GRPC_INTERNAL, "bad grpc framing")
                return
            parts = path.strip("/").split("/")
            if len(parts) != 2:
                self._respond_error(st.id, GRPC_UNIMPLEMENTED,
                                    f"bad path {path!r}")
                return
            service, method_name = parts
            timeout_s = parse_grpc_timeout(h.get("grpc-timeout"))
            deadline = (time.monotonic() + timeout_s) if timeout_s else None
            resp, code, text = self._server.invoke_grpc(service, method_name,
                                                        payload, h,
                                                        peer_sid=self.sid)
            if deadline is not None and time.monotonic() > deadline:
                self._respond_error(st.id, GRPC_DEADLINE_EXCEEDED,
                                    "deadline exceeded on server")
                return
            if code != 0:
                self._respond_error(st.id, err_to_grpc(code), text)
                return
            if not self.claim_responder(st.id):
                return   # shed/reset while the handler ran: stay silent
            enc_name, tx_codec = response_codec_for(h)
            if isinstance(resp, (bytes, bytearray, memoryview)):
                framed = grpc_frame_auto(bytes(resp), tx_codec)
                # unary fast path: whole response in one socket write
                if self.send_response_joined(st.id,
                                             self._resp_headers(enc_name),
                                             framed, [("grpc-status", "0")]):
                    return
                self.send_headers(st.id, self._resp_headers(enc_name))
                self.send_data(st.id, framed, end_stream=False)
            else:
                self.send_headers(st.id, self._resp_headers(enc_name))
                # SERVER-STREAMING: transmission runs on a DEDICATED
                # thread — a long stream (or a slow reader holding the h2
                # window at zero) must not park one of the bounded shared
                # grpc workers for its whole lifetime and starve unary
                # dispatch.  The thread takes ownership of resp, the
                # stream slot, and the stream close.
                body, resp = resp, None
                if not self._acquire_stream_slot(st.id):
                    resp = body     # finally-close; trailers report it
                    self.send_headers(
                        st.id,
                        [("grpc-status", str(GRPC_RESOURCE_EXHAUSTED)),
                         ("grpc-message", "too many concurrent streams")],
                        end_stream=True)
                    return
                handed_off = True
                threading.Thread(target=self._transmit_stream,
                                 args=(st, body, tx_codec), daemon=True,
                                 name=f"grpc-stream-tx-{st.id}").start()
                return
            self.send_headers(st.id, [("grpc-status", "0")], end_stream=True)
        except errors.RpcError:
            pass  # stream reset / connection died while responding
        except Exception:  # pragma: no cover - handler bug guard
            import traceback
            traceback.print_exc()
        finally:
            if not handed_off:
                # a streaming response abandoned BEFORE hand-off (error
                # branch, deadline branch, send failure) must run its
                # cleanup NOW — close() works even on a never-started
                # body (deferred accounting, session give-back)
                if hasattr(resp, "close"):
                    try:
                        resp.close()
                    except Exception:
                        pass
                self.close_stream(st.id)

    def _resp_headers(self, enc_name: Optional[str]) -> list[tuple[str, str]]:
        """Response HEADERS: status, content type, our codec menu, and
        the negotiated response encoding when one was picked."""
        headers = [(":status", "200"),
                   ("content-type", "application/grpc"),
                   ("grpc-accept-encoding", GRPC_ACCEPT_ENCODING)]
        if enc_name:
            headers.append(("grpc-encoding", enc_name))
        return headers

    def _transmit_stream(self, st: _StreamState, body,
                         codec: Optional[tuple] = None) -> None:
        """Send one streaming response to its end: each item one
        length-prefixed frame, then trailers.  A transport error (stream
        reset by the client's cancel, dead connection) stops quietly —
        trailers on a reset stream would be a protocol violation; a
        handler error becomes an error-trailer.  Cleanup (body.close(),
        which runs the server's deferred accounting) is unconditional."""
        try:
            try:
                for item in body:
                    self.send_data(st.id, grpc_frame_auto(bytes(item), codec),
                                   end_stream=False)
            except errors.RpcError:
                return  # reset / dead connection: no trailers possible
            except Exception as e:
                try:
                    self.send_headers(
                        st.id,
                        [("grpc-status", str(GRPC_INTERNAL)),
                         ("grpc-message",
                          f"{type(e).__name__}: {e}"[:1024])],
                        end_stream=True)
                except errors.RpcError:
                    pass
                return
            try:
                self.send_headers(st.id, [("grpc-status", "0")],
                                  end_stream=True)
            except errors.RpcError:
                pass
        finally:
            if hasattr(body, "close"):
                try:
                    body.close()
                except Exception:
                    pass
            with self._bidi_lock:
                self._bidi_rx.pop(st.id, None)
            self._release_stream_slot(st.id)
            self.close_stream(st.id)

    def _respond_error(self, stream_id: int, status: int, msg: str) -> None:
        # liveness guard: once a stream is shed/RST/closed (popped from
        # _streams) or another responder claimed it, a late responder —
        # e.g. a parked bidi handler that unparks AFTER the backlog shed
        # already sent trailers — must stay silent.  A second HEADERS on
        # a closed stream is a connection-level PROTOCOL_ERROR to a
        # conforming peer (the native plane guards this with
        # st->closed_local).  The claim is atomic under _fc (ADVICE r5):
        # check-then-act here used to race a finishing handler whose own
        # guard passed before close_stream ran.
        if not self.claim_responder(stream_id):
            return
        self.send_headers(stream_id, [
            (":status", "200"),
            ("content-type", "application/grpc"),
            ("grpc-status", str(status)),
            ("grpc-message", msg.replace("\n", " ")[:1024]),
        ], end_stream=True)


class GrpcChannel:
    """Unary gRPC client over one h2 connection (http2_rpc_protocol.cpp
    client role).  Thread-safe; concurrent calls multiplex as h2 streams
    with odd ids.

        ch = GrpcChannel("127.0.0.1:8000")
        resp_bytes = ch.call("example.Echo", "Echo", payload_bytes)

    compression="gzip"/"deflate" compresses request messages ≥1KB and
    advertises the codec via grpc-encoding; responses decompress per the
    server's grpc-encoding header either way (grpc.cpp negotiation).
    """

    def __init__(self, address: str, timeout_ms: int = 5000,
                 compression: Optional[str] = None, tls_context=None,
                 tls_server_hostname: Optional[str] = None):
        host, _, port = address.rpartition(":")
        self._addr = (host or "127.0.0.1", int(port))
        self._timeout_ms = timeout_ms
        self._enc_name = None if compression in (None, "identity") \
            else compression
        self._tx_codec = grpc_codec(compression)   # raises on unknown
        # in-socket TLS (h2 over TLS; rpc/tls_engine.py)
        self._tls = (tls_context, tls_server_hostname or self._addr[0]) \
            if tls_context is not None else None
        self._lock = threading.Lock()
        self._conn: Optional[_GrpcClientConnection] = None

    def _with_encoding(self, md: list[tuple[str, str]]
                       ) -> tuple[list[tuple[str, str]], Optional[tuple]]:
        """(metadata, effective tx codec).  A user-supplied grpc-encoding
        header WINS over the channel's compression setting — the frames
        must match whatever header actually goes on the wire (sending
        gzip bytes under an 'identity' header is a protocol error the
        server rightly rejects)."""
        for k, v in md:
            if k == "grpc-encoding":
                try:
                    return md, grpc_codec(v)
                except NotImplementedError:
                    # codec we can't produce: ship frames uncompressed
                    # (flag 0 is legal under any grpc-encoding header)
                    # and let the server's negotiation answer
                    return md, None
        if self._enc_name:
            return [("grpc-encoding", self._enc_name)] + md, self._tx_codec
        return md, None

    def _ensure(self) -> "_GrpcClientConnection":
        with self._lock:
            if self._conn is None or not self._conn.alive():
                self._conn = _GrpcClientConnection(*self._addr,
                                                   tls=self._tls)
            return self._conn

    def _with_deadline(self, metadata, timeout_ms):
        """DEADLINE PROPAGATION (unary calls only): stamp grpc-timeout so
        the server stops working on a call the client has abandoned.
        Streaming calls do NOT auto-stamp — their channel timeout is a
        per-message/production budget, not a whole-call deadline, and
        advertising it would have spec-compliant peers kill any stream
        outliving one timeout span.  Callers may always supply their own
        grpc-timeout in metadata."""
        md = list(metadata or [])
        ms = timeout_ms or self._timeout_ms
        if ms and ms > 0 and not any(k == "grpc-timeout" for k, _ in md):
            # TimeoutValue is at most 8 digits: promote the unit until
            # the number fits (m -> S -> M -> H)
            ms_i = int(ms)
            for unit, div in (("m", 1), ("S", 1000), ("M", 60_000),
                              ("H", 3_600_000)):
                v = ms_i // div
                if v < 10**8:
                    value, out_unit = v, unit
                    break
            else:
                value, out_unit = 10**8 - 1, "H"   # saturate: ~11kyr
            md.append(("grpc-timeout", f"{value}{out_unit}"))
        return md

    def acall(self, service: str, method: str, payload: bytes,
              metadata: Optional[list[tuple[str, str]]] = None,
              timeout_ms: Optional[int] = None) -> Future:
        md, codec = self._with_encoding(
            self._with_deadline(metadata, timeout_ms))
        return self._ensure().start_call(service, method, payload, md,
                                         codec=codec)

    def call(self, service: str, method: str, payload: bytes,
             timeout_ms: Optional[int] = None,
             metadata: Optional[list[tuple[str, str]]] = None) -> bytes:
        fut = self.acall(service, method, payload, metadata, timeout_ms)
        try:
            return fut.result((timeout_ms or self._timeout_ms) / 1e3)
        except TimeoutError:
            raise errors.RpcError(errors.ERPCTIMEDOUT, "grpc call timed out")

    def call_client_stream(self, service: str, method: str, requests,
                           timeout_ms: Optional[int] = None,
                           metadata: Optional[list[tuple[str, str]]] = None
                           ) -> bytes:
        """CLIENT-STREAMING call: ships one length-prefixed frame per
        item of `requests`, ends the stream, and returns the single
        response.  The server handler receives the full message list."""
        conn = self._ensure()
        fut: Future = Future()
        stream_id = 0
        try:
            # the explicit marker (not frame counting) makes a 1- or
            # 0-message client stream deliver a LIST to the handler,
            # indistinguishable from the N-message case.  No auto
            # grpc-timeout: request production time is unbounded (see
            # _with_deadline).
            md, codec = self._with_encoding(
                [("grpc-client-streaming", "1")] + list(metadata or []))
            stream_id = conn._begin_call(service, method, None, md,
                                         conn._calls, fut)
            for msg in requests:
                conn.send_data(stream_id,
                               grpc_frame_auto(bytes(msg), codec),
                               end_stream=False)
            conn.send_data(stream_id, b"", end_stream=True)
        except Exception as e:
            with conn._calls_lock:
                conn._calls.pop(stream_id, None)
            if stream_id:
                # the server has HEADERS + partial DATA: an abandoned
                # stream must be RESET (RFC 7540 §6.4), or its state
                # leaks server-side until the connection dies
                try:
                    conn.send_rst(stream_id, 0x8)   # CANCEL
                except Exception:
                    pass
                conn.close_stream(stream_id)
            if not fut.done():
                fut.set_exception(
                    e if isinstance(e, errors.RpcError) else
                    errors.RpcError(errors.EFAILEDSOCKET, str(e)))
        try:
            return fut.result((timeout_ms or self._timeout_ms) / 1e3)
        except TimeoutError:
            raise errors.RpcError(errors.ERPCTIMEDOUT,
                                  "grpc client-stream call timed out")

    def call_bidi(self, service: str, method: str,
                  timeout_ms: Optional[int] = None,
                  metadata: Optional[list[tuple[str, str]]] = None
                  ) -> "GrpcBidiCall":
        """INTERLEAVED BIDI call: returns a handle with send() /
        done_writing() for the request side and iterator semantics for
        the response side — both directions live on one open h2 stream,
        so a conversational handler can answer each message as it
        arrives."""
        conn = self._ensure()
        md, codec = self._with_encoding(
            [("grpc-bidi", "1")] + list(metadata or []))
        sink, stream_id = conn.start_stream_call(service, method, None, md)
        return GrpcBidiCall(conn, stream_id, sink,
                            (timeout_ms or self._timeout_ms) / 1e3,
                            codec=codec)

    def call_stream(self, service: str, method: str, payload: bytes,
                    timeout_ms: Optional[int] = None,
                    metadata: Optional[list[tuple[str, str]]] = None):
        """SERVER-STREAMING call: yields each response message as its
        gRPC frame arrives (incremental — messages are consumed off the
        open h2 stream, not buffered until trailers).  Raises RpcError on
        a non-zero grpc-status trailer; the per-message timeout is the
        channel timeout.

        The stream opens (and the request ships) EAGERLY, before the
        first iteration — a plain function returning an inner generator,
        so call latency/timeouts start at call time, not first-next."""
        per_msg_s = (timeout_ms or self._timeout_ms) / 1e3
        conn = self._ensure()
        # no auto grpc-timeout: the channel timeout is PER MESSAGE here,
        # not a whole-stream deadline (see _with_deadline)
        md, codec = self._with_encoding(list(metadata or []))
        sink, stream_id = conn.start_stream_call(service, method, payload,
                                                 md, codec=codec)
        return GrpcServerStreamCall(conn, stream_id, sink, per_msg_s)

    def close(self) -> None:
        with self._lock:
            if self._conn is not None:
                self._conn.close()
                self._conn = None


class GrpcServerStreamCall:
    """Iterator over one server-streaming response.  An ITERATOR OBJECT,
    not a generator: a call that is dropped without ever being iterated
    still cancels the server-side stream (close() works pre-start, and
    __del__ backstops a leaked handle) — a generator's finally would
    never run in that case."""

    def __init__(self, conn: "_GrpcClientConnection", stream_id: int,
                 sink: "queue.Queue", per_msg_timeout_s: float):
        self._conn = conn
        self._sid = stream_id
        self._sink = sink
        self._timeout_s = per_msg_timeout_s
        self._finished = False

    def __iter__(self):
        return self

    def __next__(self):
        if self._finished:
            raise StopIteration
        try:
            item = self._sink.get(timeout=self._timeout_s)
        except queue.Empty:
            self.close()
            raise errors.RpcError(errors.ERPCTIMEDOUT,
                                  "grpc stream message timed out")
        if item is _STREAM_END:
            self._finished = True
            raise StopIteration
        if isinstance(item, Exception):
            self._finished = True
            raise item
        return item

    def close(self) -> None:
        """Abandon the stream: tell the server to stop transmitting."""
        if not self._finished:
            self._finished = True
            if self._sid:
                self._conn.cancel_stream_call(self._sid)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):  # leaked handle backstop; close() is the real path
        try:
            self.close()
        except Exception:
            pass


class GrpcBidiCall:
    """Client handle for one interleaved bidi stream: send() request
    messages (done_writing() half-closes), iterate responses as their
    frames arrive.  Abandoning the iterator cancels the stream."""

    def __init__(self, conn: "_GrpcClientConnection", stream_id: int,
                 sink: "queue.Queue", per_msg_timeout_s: float,
                 codec: Optional[tuple] = None):
        self._conn = conn
        self._sid = stream_id
        self._sink = sink
        self._timeout_s = per_msg_timeout_s
        self._codec = codec
        self._write_closed = False
        self._finished = False

    def send(self, msg: bytes) -> None:
        if self._write_closed:
            raise errors.RpcError(errors.EREQUEST,
                                  "bidi request side already closed")
        self._conn.send_data(self._sid,
                             grpc_frame_auto(bytes(msg), self._codec),
                             end_stream=False)

    def done_writing(self) -> None:
        if not self._write_closed:
            self._write_closed = True
            self._conn.send_data(self._sid, b"", end_stream=True)

    def __iter__(self):
        return self

    def __next__(self):
        if self._finished:
            raise StopIteration
        try:
            item = self._sink.get(timeout=self._timeout_s)
        except queue.Empty:
            self.cancel()
            raise errors.RpcError(errors.ERPCTIMEDOUT,
                                  "bidi response message timed out")
        if item is _STREAM_END:
            self._finished = True
            raise StopIteration
        if isinstance(item, Exception):
            self._finished = True
            raise item
        return item

    def cancel(self) -> None:
        if not self._finished:
            self._finished = True
            self._conn.cancel_stream_call(self._sid)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if not self._finished:
            # drained or abandoned: make sure the stream dies either way
            self.cancel()


class _GrpcClientConnection(H2Connection):
    def __init__(self, host: str, port: int, tls=None):
        # every field the native callbacks touch must exist BEFORE
        # connect(): the dispatcher thread may fire _on_message/_on_failed
        # the moment the socket registers
        super().__init__(None, is_server=False)
        self._authority = f"{host}:{port}"
        self._next_stream = 1
        self._calls: dict[int, Future] = {}
        self._sinks: dict[int, "queue.Queue"] = {}   # streaming calls
        self._calls_lock = threading.Lock()
        tp = Transport.instance()
        self.sid = tp.connect(host, port, self._on_message, self._on_failed)
        if tls is not None:
            # h2-over-TLS: wrap before the preface leaves (the preface
            # below is plaintext to US but rides the engine encrypted)
            tp.enable_tls(self.sid, tls[0], server_side=False,
                          server_hostname=tls[1])
        tp.set_protocol(self.sid, MSG_H2)
        self.send_preface_and_settings()

    def alive(self) -> bool:
        return (not self._goaway and not self._fatal
                and self._tp.alive(self.sid))

    def on_fatal(self) -> None:
        # fail every in-flight call/sink immediately; alive() is already
        # False (self._fatal), so GrpcChannel._ensure reconnects next
        # call.  Then close the socket (base behavior): _ensure drops the
        # reference without closing, so leaving it open would leak the fd
        # until the peer reacts to GOAWAY.
        self._on_failed(self.sid, errors.EFAILEDSOCKET)
        super().on_fatal()

    def close(self) -> None:
        try:
            self.send_goaway()
        except Exception:
            pass
        self._tp.close(self.sid)

    def _on_message(self, sid: int, kind: int, meta: bytes, body) -> None:
        if self.sid is None:
            self.sid = sid  # connect() hasn't returned yet
        if kind == MSG_H2:
            feed_frames(self, meta, body.to_bytes())

    def _on_failed(self, sid: int, err: int) -> None:
        with self._calls_lock:
            calls, self._calls = self._calls, {}
            sinks, self._sinks = self._sinks, {}
        for fut in calls.values():
            if not fut.done():
                fut.set_exception(errors.RpcError(
                    errors.EFAILEDSOCKET, "h2 connection lost"))
        for sink in sinks.values():
            sink.put(errors.RpcError(errors.EFAILEDSOCKET,
                                     "h2 connection lost"))

    def _begin_call(self, service: str, method: str,
                    payload: Optional[bytes],
                    metadata: list[tuple[str, str]], registry: dict,
                    completion, codec: Optional[tuple] = None) -> int:
        """Shared open-and-send for unary and streaming calls: allocate
        the id AND send HEADERS under one lock (RFC 7540 §5.1.1 requires
        stream ids to hit the wire in increasing order, so the two steps
        must not interleave across threads), register the completion in
        `registry`, then ship the single request frame.  payload=None
        opens the stream WITHOUT ending it (client-streaming: the caller
        ships request frames itself).  Returns the stream id; raises
        after unregistering on ANY failure — including a send_headers
        failure inside the lock, which must not leak the registry entry
        or the open_stream window state."""
        framed = grpc_frame_auto(payload, codec) if payload is not None \
            else None
        with self._calls_lock:
            stream_id = self._next_stream
            self._next_stream += 2
            registry[stream_id] = completion
            self.open_stream(stream_id)  # track our send window
            try:
                headers = [(":method", "POST"), (":scheme", "http"),
                           (":path", f"/{service}/{method}"),
                           (":authority", self._authority),
                           ("content-type", "application/grpc"),
                           ("grpc-accept-encoding", GRPC_ACCEPT_ENCODING),
                           ("te", "trailers")] + metadata
                # unary fast path: HEADERS + DATA in one socket write
                # (still under _calls_lock — stream ids must hit the
                # wire in increasing order)
                if framed is not None and \
                        self.send_request_joined(stream_id, headers, framed):
                    return stream_id
                self.send_headers(stream_id, headers)
            except Exception:
                registry.pop(stream_id, None)
                self.close_stream(stream_id)
                raise
        if framed is None:
            return stream_id
        try:
            self.send_data(stream_id, framed, end_stream=True)
        except Exception:
            with self._calls_lock:
                registry.pop(stream_id, None)
            self.close_stream(stream_id)
            raise
        return stream_id

    def start_call(self, service: str, method: str, payload: bytes,
                   metadata: list[tuple[str, str]],
                   codec: Optional[tuple] = None) -> Future:
        fut: Future = Future()
        try:
            self._begin_call(service, method, payload, metadata,
                             self._calls, fut, codec=codec)
        except Exception as e:
            if not fut.done():
                fut.set_exception(e)
        return fut

    def start_stream_call(self, service: str, method: str, payload: bytes,
                          metadata: list[tuple[str, str]],
                          codec: Optional[tuple] = None):
        """Open a server-streaming call; returns (sink, stream_id): the
        queue call_stream drains (messages, then _STREAM_END or an
        exception) and the id used to cancel an abandoned stream."""
        sink: "queue.Queue" = queue.Queue()
        stream_id = 0
        try:
            stream_id = self._begin_call(service, method, payload,
                                         metadata, self._sinks, sink,
                                         codec=codec)
        except Exception as e:
            sink.put(e if isinstance(e, errors.RpcError) else
                     errors.RpcError(errors.EFAILEDSOCKET, str(e)))
        return sink, stream_id

    def cancel_stream_call(self, stream_id: int) -> None:
        """Abandoned streaming call: stop delivery and tell the server to
        stop transmitting (RST_STREAM CANCEL) instead of letting it ship
        the rest of the response into an unread queue."""
        with self._calls_lock:
            sink = self._sinks.pop(stream_id, None)
        if sink is None:
            return
        try:
            self.send_rst(stream_id, 0x8)   # CANCEL
        except Exception:
            pass
        self.close_stream(stream_id)

    def _drain_stream_frames(self, st: _StreamState, sink) -> bool:
        """Pop complete length-prefixed messages off the stream buffer
        into the sink, decompressing per the response's grpc-encoding.
        Returns False on a framing error (sink fed the exception)."""
        if st.rx_codec is _CODEC_UNSET:
            try:
                st.rx_codec = negotiated_codec(dict(st.headers))
            except NotImplementedError as e:
                st.rx_codec = None
                sink.put(errors.RpcError(errors.ERESPONSE, str(e)))
                return False
        msgs, err = pop_grpc_frames(st.data, st.rx_codec)
        for m in msgs:
            sink.put(m)
        if err is not None:
            sink.put(errors.RpcError(errors.ERESPONSE, err))
            return False
        return True

    def on_stream_data(self, st: _StreamState) -> None:
        with self._calls_lock:
            sink = self._sinks.get(st.id)
        if sink is not None and not self._drain_stream_frames(st, sink):
            with self._calls_lock:
                self._sinks.pop(st.id, None)
            self.send_rst(st.id, 0x2)
            self.close_stream(st.id)

    def on_stream_complete(self, st: _StreamState) -> None:
        self.close_stream(st.id)
        with self._calls_lock:
            fut = self._calls.pop(st.id, None)
            sink = self._sinks.pop(st.id, None)
        h = dict(st.headers)
        t = dict(st.trailers) if st.trailers else h
        try:
            status = int(t.get("grpc-status", "0"))
        except ValueError:
            status = GRPC_UNKNOWN
        failed = h.get(":status", "200") != "200" or status != 0
        if sink is not None:
            if failed:
                msg = t.get("grpc-message", f"grpc-status {status}")
                sink.put(errors.RpcError(grpc_to_err(status), msg))
            elif not self._drain_stream_frames(st, sink):
                pass  # framing error already fed to the sink
            elif st.data:
                # clean trailers with a partial frame still buffered:
                # the unary path calls this 'truncated grpc frame' —
                # never report a clean end with a message silently lost
                sink.put(errors.RpcError(errors.ERESPONSE,
                                         "truncated grpc frame"))
            else:
                sink.put(_STREAM_END)
            return
        if fut is None or fut.done():
            return
        if failed:
            msg = t.get("grpc-message", f"grpc-status {status}")
            fut.set_exception(errors.RpcError(grpc_to_err(status), msg))
            return
        try:
            msgs = parse_grpc_frames(bytes(st.data), negotiated_codec(h))
            fut.set_result(msgs[0] if msgs else b"")
        except (ValueError, NotImplementedError) as e:
            fut.set_exception(errors.RpcError(errors.ERESPONSE, str(e)))

    def on_stream_reset(self, stream_id: int, code: int) -> None:
        with self._calls_lock:
            fut = self._calls.pop(stream_id, None)
            sink = self._sinks.pop(stream_id, None)
        if fut is not None and not fut.done():
            fut.set_exception(errors.RpcError(
                errors.EINTERNAL, f"stream reset by peer (h2 error {code})"))
        if sink is not None:
            sink.put(errors.RpcError(
                errors.EINTERNAL, f"stream reset by peer (h2 error {code})"))

    def on_goaway(self, last_stream: int) -> None:
        """Fail calls the peer will never process (ids above last_stream)
        immediately instead of letting them ride out their full timeout."""
        with self._calls_lock:
            doomed = {sid: f for sid, f in self._calls.items()
                      if sid > last_stream}
            for sid in doomed:
                del self._calls[sid]
            doomed_sinks = {sid: s for sid, s in self._sinks.items()
                            if sid > last_stream}
            for sid in doomed_sinks:
                del self._sinks[sid]
        err = errors.RpcError(errors.EFAILEDSOCKET,
                              "connection going away (h2 GOAWAY)")
        for sid, fut in doomed.items():
            self.close_stream(sid)
            if not fut.done():
                fut.set_exception(err)
        for sid, sink in doomed_sinks.items():
            self.close_stream(sid)
            sink.put(err)
