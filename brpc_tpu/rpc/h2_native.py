"""Python bridge for the NATIVE h2/gRPC server data plane.

Reference: src/brpc/policy/http2_rpc_protocol.cpp — the reference's h2
server parses frames, HPACK and gRPC framing natively and surfaces whole
requests to service code.  Round 5 moved our plane's framing into
src/cc/net/h2.{h,cc}; this module is the Python half: the native session
upcalls ONE event per request (unary) or per message (streaming), and
this bridge dispatches into ``Server.invoke_grpc`` — the same gates
(auth, interceptor, limiters, rpcz) as every other protocol — then
answers through the native response packers (``brpc_h2_respond_unary``
etc.), which do HPACK encode, DATA framing and flow control in C++.

Semantics mirror rpc/h2.py ``GrpcServerConnection`` (the pure-Python
plane, still used by the client side and as the opt-out fallback):
unary dispatch on the shared grpc worker pool, client-streaming
delivered as a message list at END, bidi dispatched at HEADERS with a
live request iterator, server-streaming transmitted on a dedicated
thread, per-connection streaming-call slots.
"""
from __future__ import annotations

import ctypes
import queue
import threading
import time
from typing import Optional

from brpc_tpu import errors
from brpc_tpu.rpc.h2 import (GRPC_ACCEPT_ENCODING, GRPC_DEADLINE_EXCEEDED,
                             GRPC_INTERNAL, GRPC_RESOURCE_EXHAUSTED,
                             GRPC_UNIMPLEMENTED, _grpc_executor, _inflate,
                             _STREAM_END, err_to_grpc, grpc_codec,
                             parse_grpc_timeout, response_codec_for,
                             GRPC_COMPRESS_MIN)

# event kinds (src/cc/net/h2.h EventKind)
EV_UNARY = 0
EV_HEADERS = 1
EV_MESSAGE = 2
EV_END = 3
EV_RESET = 4

# per-connection bound on concurrently-SERVED streaming calls (each
# holds a dedicated thread) — mirrors GrpcServerConnection
MAX_STREAMING_CALLS = 128

# per-call rx backlog bound shared with the Python plane (defined next
# to the other h2 bounds): the native session tops up flow-control
# windows on PARSE (not handler consumption), so without this a client
# can flood a slow handler's queue without ever hitting h2 flow control
from brpc_tpu.rpc.h2 import (MAX_BUFFERED_BIDI_MSGS,  # noqa: E402
                             grpc_backlog_sheds)


def _expose_native_counters() -> None:
    """Native session counters on /vars (console parity: the gRPC plane's
    traffic is visible next to every other protocol's)."""
    import ctypes as _ct

    from brpc_tpu._core.lib import core as _core
    from brpc_tpu.bvar import PassiveStatus

    def _stat(idx):
        def get():
            vals = [_ct.c_int64(), _ct.c_int64(), _ct.c_int64()]
            _core.brpc_h2_native_stats(*[_ct.byref(v) for v in vals])
            return vals[idx].value
        return get

    PassiveStatus(_stat(0)).expose("h2_native_requests")
    PassiveStatus(_stat(1)).expose("h2_native_responses")
    PassiveStatus(_stat(2)).expose("h2_python_events")


_expose_native_counters()


def _decode_headers(flat: bytes) -> dict:
    """'name\\0value\\0' pairs -> dict (last wins, matching dict(st.headers))."""
    h: dict[str, str] = {}
    parts = flat.split(b"\0")
    for i in range(0, len(parts) - 1, 2):
        h[parts[i].decode("utf-8", "replace")] = \
            parts[i + 1].decode("utf-8", "replace")
    return h


class _StreamCall:
    """One in-flight STREAMING request on a native session."""

    __slots__ = ("headers", "service", "method", "codec", "rx", "collect",
                 "bidi", "bad")

    def __init__(self, headers: dict, service: str, method: str):
        self.headers = headers
        self.service = service
        self.method = method
        self.codec = None
        self.rx: Optional[queue.Queue] = None    # bidi feed
        self.collect: Optional[list] = None      # client-streaming buffer
        self.bidi = headers.get("grpc-bidi") == "1"
        self.bad = False


class NativeH2Bridge:
    """Routes native h2 session events for ONE server's connections."""

    def __init__(self, server):
        self._server = server
        self._core = None         # bound lazily (lib import cycle)
        self._mu = threading.Lock()
        # (sid, stream_id) -> _StreamCall for streaming requests
        self._calls: dict[tuple[int, int], _StreamCall] = {}
        self._slots: dict[int, set[int]] = {}    # sid -> stream ids

    # ---- native send wrappers -------------------------------------------

    def _lib(self):
        if self._core is None:
            from brpc_tpu._core.lib import core
            self._core = core
        return self._core

    @staticmethod
    def _flat_kv(pairs: list[tuple[str, str]]) -> bytes:
        out = bytearray()
        for k, v in pairs:
            out += k.encode() + b"\0" + v.encode() + b"\0"
        return bytes(out)

    def _respond_unary(self, sid: int, stream_id: int, payload: bytes,
                       enc_name: Optional[str], codec) -> None:
        core = self._lib()
        extra = [("grpc-accept-encoding", GRPC_ACCEPT_ENCODING)]
        if (codec is not None and enc_name
                and len(payload) >= GRPC_COMPRESS_MIN):
            # negotiated compression: headers carry grpc-encoding, the
            # message ships with the compressed flag
            extra.append(("grpc-encoding", enc_name))
            kv = self._flat_kv(extra)
            if core.brpc_h2_send_response_headers(sid, stream_id, kv,
                                                  len(kv)) != 0:
                return
            comp = codec[0](payload)
            if core.brpc_h2_send_message(sid, stream_id, comp, len(comp),
                                         1) != 0:
                return
            core.brpc_h2_send_trailers(sid, stream_id, 0, None, 0, None, 0)
            return
        kv = self._flat_kv(extra)
        core.brpc_h2_respond_unary(sid, stream_id, 0, None, 0, payload,
                                   len(payload), kv, len(kv))

    def _respond_error(self, sid: int, stream_id: int, status: int,
                       msg: str) -> None:
        m = msg.replace("\n", " ")[:1024].encode()
        self._lib().brpc_h2_respond_unary(sid, stream_id, status, m, len(m),
                                          None, 0, None, 0)

    # ---- streaming slots -------------------------------------------------

    def _acquire_slot(self, sid: int, stream_id: int) -> bool:
        with self._mu:
            slots = self._slots.setdefault(sid, set())
            if stream_id in slots:
                return True
            if len(slots) >= MAX_STREAMING_CALLS:
                return False
            slots.add(stream_id)
            return True

    def _release_slot(self, sid: int, stream_id: int) -> None:
        with self._mu:
            slots = self._slots.get(sid)
            if slots is not None:
                slots.discard(stream_id)
                if not slots:
                    self._slots.pop(sid, None)

    # ---- event entry (runs on the socket's FIFO lane) --------------------

    def on_event(self, sid: int, stream_id: int, kind: int, service: str,
                 method: str, headers_flat: bytes, body: Optional[bytes],
                 mflags: int) -> None:
        if kind == EV_UNARY:
            h = _decode_headers(headers_flat)
            _grpc_executor().submit(self._process_unary, sid, stream_id,
                                    service, method, h, body or b"", mflags)
            return
        key = (sid, stream_id)
        if kind == EV_HEADERS:
            h = _decode_headers(headers_flat)
            call = _StreamCall(h, service, method)
            try:
                call.codec = grpc_codec(h.get("grpc-encoding"))
            except NotImplementedError as e:
                self._respond_error(sid, stream_id, GRPC_UNIMPLEMENTED,
                                    str(e))
                return
            with self._mu:
                self._calls[key] = call
            if call.bidi:
                if not self._acquire_slot(sid, stream_id):
                    with self._mu:
                        self._calls.pop(key, None)
                    self._respond_error(sid, stream_id,
                                        GRPC_RESOURCE_EXHAUSTED,
                                        "too many concurrent streams")
                    return
                call.rx = queue.Queue()
                threading.Thread(target=self._process_bidi,
                                 args=(sid, stream_id, call), daemon=True,
                                 name=f"grpc-bidi-rx-{stream_id}").start()
            else:
                call.collect = []
            return
        with self._mu:
            call = self._calls.get(key)
        if call is None:
            return
        if kind == EV_MESSAGE:
            if call.bad:
                return
            try:
                msg = _inflate(mflags & 1, body or b"", call.codec)
            except Exception as e:
                call.bad = True
                if call.rx is not None:
                    call.rx.put(errors.RpcError(errors.EREQUEST, str(e)))
                else:
                    self._respond_error(sid, stream_id, GRPC_INTERNAL,
                                        f"bad grpc framing: {e}")
                return
            if (mflags & 1) and call.codec is None:
                call.bad = True
                err = errors.RpcError(
                    errors.EREQUEST,
                    "compressed grpc message without grpc-encoding")
                if call.rx is not None:
                    call.rx.put(err)
                else:
                    self._respond_error(sid, stream_id, GRPC_INTERNAL,
                                        str(err))
                return
            if call.rx is not None:
                # budget check, not a bounded queue: a blocking put would
                # stall the socket FIFO lane (head-of-line blocking every
                # stream on the connection), and the error/END sentinels
                # below must never be droppable.  qsize is approximate —
                # fine for a DoS bound.  (Defense in depth: on this
                # plane the socket FIFO's own 256-event depth usually
                # sheds a flood first; this cap stands when events drain
                # into rx faster than the handler consumes.)
                if call.rx.qsize() >= MAX_BUFFERED_BIDI_MSGS:
                    grpc_backlog_sheds.add(1)
                    call.bad = True
                    with self._mu:
                        self._calls.pop(key, None)
                    call.rx.put(errors.RpcError(
                        errors.ELIMIT,
                        "bidi rx backlog exceeded: handler too slow "
                        "for the send rate"))
                    self._respond_error(sid, stream_id,
                                        GRPC_RESOURCE_EXHAUSTED,
                                        "bidi rx backlog exceeded")
                    return
                call.rx.put(msg)
            elif call.collect is not None:
                if len(call.collect) >= MAX_BUFFERED_BIDI_MSGS:
                    grpc_backlog_sheds.add(1)
                    call.bad = True
                    call.collect = None
                    self._respond_error(sid, stream_id,
                                        GRPC_RESOURCE_EXHAUSTED,
                                        "client-stream backlog exceeded")
                    return
                call.collect.append(msg)
            return
        if kind == EV_END:
            if call.rx is not None:
                call.rx.put(_STREAM_END)
                with self._mu:
                    self._calls.pop(key, None)
                return
            with self._mu:
                self._calls.pop(key, None)
            if call.bad:
                return
            _grpc_executor().submit(self._process_collected, sid, stream_id,
                                    call.service, call.method, call)
            return
        if kind == EV_RESET:
            with self._mu:
                self._calls.pop(key, None)
            if call.rx is not None:
                call.rx.put(errors.RpcError(errors.ECANCELED,
                                            "stream reset by peer"))
            return

    def on_connection_failed(self, sid: int) -> None:
        """The connection died: unblock every parked bidi handler."""
        with self._mu:
            dead = [(k, c) for k, c in self._calls.items() if k[0] == sid]
            for k, _ in dead:
                self._calls.pop(k, None)
            self._slots.pop(sid, None)
        for _, call in dead:
            if call.rx is not None:
                call.rx.put(errors.RpcError(errors.ECANCELED,
                                            "h2 connection lost"))

    # ---- dispatch paths (grpc worker pool / dedicated threads) -----------

    def _process_unary(self, sid: int, stream_id: int, service: str,
                       method: str, h: dict, body: bytes,
                       mflags: int) -> None:
        resp = None
        handed_off = False
        try:
            try:
                codec = grpc_codec(h.get("grpc-encoding"))
            except NotImplementedError as e:
                self._respond_error(sid, stream_id, GRPC_UNIMPLEMENTED,
                                    str(e))
                return
            if mflags >= 0 and mflags & 1:
                if codec is None:
                    self._respond_error(
                        sid, stream_id, GRPC_INTERNAL,
                        "compressed grpc message without grpc-encoding")
                    return
                try:
                    body = codec[1](body)
                except Exception as e:
                    self._respond_error(sid, stream_id, GRPC_INTERNAL,
                                        f"bad grpc framing: {e}")
                    return
            if not service or not method:
                self._respond_error(sid, stream_id, GRPC_UNIMPLEMENTED,
                                    "bad path")
                return
            # a marked client-stream delivers the full message LIST even
            # for 0/1 messages (the header decides the contract);
            # mflags < 0 = the request ended with NO message at all
            if h.get("grpc-client-streaming") == "1":
                payload = [] if mflags < 0 else [body]
            else:
                payload = body
            timeout_s = parse_grpc_timeout(h.get("grpc-timeout"))
            deadline = (time.monotonic() + timeout_s) if timeout_s else None
            resp, code, text = self._server.invoke_grpc(
                service, method, payload, h, peer_sid=sid)
            if deadline is not None and time.monotonic() > deadline:
                self._respond_error(sid, stream_id, GRPC_DEADLINE_EXCEEDED,
                                    "deadline exceeded on server")
                return
            if code != 0:
                self._respond_error(sid, stream_id, err_to_grpc(code), text)
                return
            enc_name, tx_codec = response_codec_for(h)
            if isinstance(resp, (bytes, bytearray, memoryview)):
                self._respond_unary(sid, stream_id, bytes(resp), enc_name,
                                    tx_codec)
                return
            # SERVER-STREAMING response to a unary request
            if not self._acquire_slot(sid, stream_id):
                self._respond_error(sid, stream_id, GRPC_RESOURCE_EXHAUSTED,
                                    "too many concurrent streams")
                return
            body_iter, resp = resp, None
            handed_off = True
            threading.Thread(target=self._transmit_stream,
                             args=(sid, stream_id, body_iter, enc_name,
                                   tx_codec), daemon=True,
                             name=f"grpc-stream-tx-{stream_id}").start()
        except errors.RpcError:
            pass
        except Exception:  # pragma: no cover - handler bug guard
            import traceback
            traceback.print_exc()
        finally:
            if not handed_off and hasattr(resp, "close"):
                try:
                    resp.close()
                except Exception:
                    pass

    def _process_collected(self, sid: int, stream_id: int, service: str,
                           method: str, call: _StreamCall) -> None:
        """Client-streaming (non-bidi): whole message list at END."""
        h = call.headers
        svc = service or h.get(":path", "").strip("/").split("/")[0]
        self._process_unary_list(sid, stream_id, svc, method, h,
                                 call.collect or [])

    def _process_unary_list(self, sid: int, stream_id: int, service: str,
                            method: str, h: dict, msgs: list) -> None:
        resp = None
        handed_off = False
        try:
            if not service or not method:
                parts = h.get(":path", "").strip("/").split("/")
                if len(parts) == 2:
                    service, method = parts
                else:
                    self._respond_error(sid, stream_id, GRPC_UNIMPLEMENTED,
                                        "bad path")
                    return
            payload = msgs if (h.get("grpc-client-streaming") == "1"
                               or len(msgs) > 1) \
                else (msgs[0] if msgs else b"")
            resp, code, text = self._server.invoke_grpc(
                service, method, payload, h, peer_sid=sid)
            if code != 0:
                self._respond_error(sid, stream_id, err_to_grpc(code), text)
                return
            enc_name, tx_codec = response_codec_for(h)
            if isinstance(resp, (bytes, bytearray, memoryview)):
                self._respond_unary(sid, stream_id, bytes(resp), enc_name,
                                    tx_codec)
                return
            if not self._acquire_slot(sid, stream_id):
                self._respond_error(sid, stream_id, GRPC_RESOURCE_EXHAUSTED,
                                    "too many concurrent streams")
                return
            body_iter, resp = resp, None
            handed_off = True
            threading.Thread(target=self._transmit_stream,
                             args=(sid, stream_id, body_iter, enc_name,
                                   tx_codec), daemon=True,
                             name=f"grpc-stream-tx-{stream_id}").start()
        except errors.RpcError:
            pass
        except Exception:  # pragma: no cover
            import traceback
            traceback.print_exc()
        finally:
            if not handed_off and hasattr(resp, "close"):
                try:
                    resp.close()
                except Exception:
                    pass

    def _process_bidi(self, sid: int, stream_id: int,
                      call: _StreamCall) -> None:
        resp = None
        handed_off = False
        rx = call.rx
        h = call.headers
        try:
            parts = h.get(":path", "").strip("/").split("/")
            if len(parts) != 2:
                self._respond_error(sid, stream_id, GRPC_UNIMPLEMENTED,
                                    "bad path")
                return
            timeout_s = parse_grpc_timeout(h.get("grpc-timeout"))
            deadline = (time.monotonic() + timeout_s) if timeout_s else None

            def request_iter():
                while True:
                    if deadline is None:
                        item = rx.get()
                    else:
                        left = deadline - time.monotonic()
                        if left <= 0:
                            raise errors.RpcError(
                                errors.ERPCTIMEDOUT,
                                "bidi deadline exceeded on server")
                        try:
                            item = rx.get(timeout=left)
                        except queue.Empty:
                            raise errors.RpcError(
                                errors.ERPCTIMEDOUT,
                                "bidi deadline exceeded on server")
                    if item is _STREAM_END:
                        return
                    if isinstance(item, Exception):
                        raise item
                    yield item

            resp, code, text = self._server.invoke_grpc(
                parts[0], parts[1], b"", h, peer_sid=sid,
                payload_iter=request_iter())
            if code != 0:
                self._respond_error(sid, stream_id, err_to_grpc(code), text)
                return
            enc_name, tx_codec = response_codec_for(h)
            if isinstance(resp, (bytes, bytearray, memoryview)):
                self._respond_unary(sid, stream_id, bytes(resp), enc_name,
                                    tx_codec)
                return
            body_iter, resp = resp, None
            handed_off = True
            self._transmit_stream(sid, stream_id, body_iter, enc_name,
                                  tx_codec, slot_held=True)
        except errors.RpcError:
            pass
        except Exception:  # pragma: no cover
            import traceback
            traceback.print_exc()
        finally:
            if not handed_off:
                with self._mu:
                    self._calls.pop((sid, stream_id), None)
                if hasattr(resp, "close"):
                    try:
                        resp.close()
                    except Exception:
                        pass
                self._release_slot(sid, stream_id)

    def _transmit_stream(self, sid: int, stream_id: int, body,
                         enc_name: Optional[str], codec,
                         slot_held: bool = True) -> None:
        """Send one streaming response to its end: headers (with the
        negotiated encoding), each item one native gRPC message, then
        trailers.  A send failure (client reset / dead connection) stops
        quietly — the native session already dropped the stream."""
        core = self._lib()
        try:
            extra = [("grpc-accept-encoding", GRPC_ACCEPT_ENCODING)]
            if enc_name:
                extra.append(("grpc-encoding", enc_name))
            kv = self._flat_kv(extra)
            core.brpc_h2_send_response_headers(sid, stream_id, kv, len(kv))
            try:
                for item in body:
                    payload = bytes(item)
                    flags = 0
                    if codec is not None and \
                            len(payload) >= GRPC_COMPRESS_MIN:
                        payload = codec[0](payload)
                        flags = 1
                    if core.brpc_h2_send_message(sid, stream_id, payload,
                                                 len(payload), flags) != 0:
                        return  # reset / dead connection
            except Exception as e:
                msg = f"{type(e).__name__}: {e}"[:1024].encode()
                core.brpc_h2_send_trailers(sid, stream_id, GRPC_INTERNAL,
                                           msg, len(msg), None, 0)
                return
            core.brpc_h2_send_trailers(sid, stream_id, 0, None, 0, None, 0)
        finally:
            if hasattr(body, "close"):
                try:
                    body.close()
                except Exception:
                    pass
            with self._mu:
                self._calls.pop((sid, stream_id), None)
            if slot_held:
                self._release_slot(sid, stream_id)
