"""HPACK (RFC 7541) header compression for the h2 protocol.

Reference: src/brpc/details/hpack.cpp (878 LoC) — static+dynamic table
indexing, integer/string primitives, Huffman coding.  This is a clean-room
implementation from the RFC; the reference file is cited for parity only.
"""
from __future__ import annotations

# ---- static table (RFC 7541 Appendix A) ----------------------------------

STATIC_TABLE: list[tuple[str, str]] = [
    (":authority", ""),
    (":method", "GET"),
    (":method", "POST"),
    (":path", "/"),
    (":path", "/index.html"),
    (":scheme", "http"),
    (":scheme", "https"),
    (":status", "200"),
    (":status", "204"),
    (":status", "206"),
    (":status", "304"),
    (":status", "400"),
    (":status", "404"),
    (":status", "500"),
    ("accept-charset", ""),
    ("accept-encoding", "gzip, deflate"),
    ("accept-language", ""),
    ("accept-ranges", ""),
    ("accept", ""),
    ("access-control-allow-origin", ""),
    ("age", ""),
    ("allow", ""),
    ("authorization", ""),
    ("cache-control", ""),
    ("content-disposition", ""),
    ("content-encoding", ""),
    ("content-language", ""),
    ("content-length", ""),
    ("content-location", ""),
    ("content-range", ""),
    ("content-type", ""),
    ("cookie", ""),
    ("date", ""),
    ("etag", ""),
    ("expect", ""),
    ("expires", ""),
    ("from", ""),
    ("host", ""),
    ("if-match", ""),
    ("if-modified-since", ""),
    ("if-none-match", ""),
    ("if-range", ""),
    ("if-unmodified-since", ""),
    ("last-modified", ""),
    ("link", ""),
    ("location", ""),
    ("max-forwards", ""),
    ("proxy-authenticate", ""),
    ("proxy-authorization", ""),
    ("range", ""),
    ("referer", ""),
    ("refresh", ""),
    ("retry-after", ""),
    ("server", ""),
    ("set-cookie", ""),
    ("strict-transport-security", ""),
    ("transfer-encoding", ""),
    ("user-agent", ""),
    ("vary", ""),
    ("via", ""),
    ("www-authenticate", ""),
]
_STATIC_BY_PAIR = {(n, v): i + 1 for i, (n, v) in enumerate(STATIC_TABLE)}
_STATIC_BY_NAME: dict[str, int] = {}
for _i, (_n, _v) in enumerate(STATIC_TABLE):
    _STATIC_BY_NAME.setdefault(_n, _i + 1)

# ---- Huffman code table (RFC 7541 Appendix B): symbol -> (code, nbits) ----

HUFFMAN_TABLE: list[tuple[int, int]] = [
    (0x1ff8, 13), (0x7fffd8, 23), (0xfffffe2, 28), (0xfffffe3, 28),
    (0xfffffe4, 28), (0xfffffe5, 28), (0xfffffe6, 28), (0xfffffe7, 28),
    (0xfffffe8, 28), (0xffffea, 24), (0x3ffffffc, 30), (0xfffffe9, 28),
    (0xfffffea, 28), (0x3ffffffd, 30), (0xfffffeb, 28), (0xfffffec, 28),
    (0xfffffed, 28), (0xfffffee, 28), (0xfffffef, 28), (0xffffff0, 28),
    (0xffffff1, 28), (0xffffff2, 28), (0x3ffffffe, 30), (0xffffff3, 28),
    (0xffffff4, 28), (0xffffff5, 28), (0xffffff6, 28), (0xffffff7, 28),
    (0xffffff8, 28), (0xffffff9, 28), (0xffffffa, 28), (0xffffffb, 28),
    (0x14, 6), (0x3f8, 10), (0x3f9, 10), (0xffa, 12),
    (0x1ff9, 13), (0x15, 6), (0xf8, 8), (0x7fa, 11),
    (0x3fa, 10), (0x3fb, 10), (0xf9, 8), (0x7fb, 11),
    (0xfa, 8), (0x16, 6), (0x17, 6), (0x18, 6),
    (0x0, 5), (0x1, 5), (0x2, 5), (0x19, 6),
    (0x1a, 6), (0x1b, 6), (0x1c, 6), (0x1d, 6),
    (0x1e, 6), (0x1f, 6), (0x5c, 7), (0xfb, 8),
    (0x7ffc, 15), (0x20, 6), (0xffb, 12), (0x3fc, 10),
    (0x1ffa, 13), (0x21, 6), (0x5d, 7), (0x5e, 7),
    (0x5f, 7), (0x60, 7), (0x61, 7), (0x62, 7),
    (0x63, 7), (0x64, 7), (0x65, 7), (0x66, 7),
    (0x67, 7), (0x68, 7), (0x69, 7), (0x6a, 7),
    (0x6b, 7), (0x6c, 7), (0x6d, 7), (0x6e, 7),
    (0x6f, 7), (0x70, 7), (0x71, 7), (0x72, 7),
    (0xfc, 8), (0x73, 7), (0xfd, 8), (0x1ffb, 13),
    (0x7fff0, 19), (0x1ffc, 13), (0x3ffc, 14), (0x22, 6),
    (0x7ffd, 15), (0x3, 5), (0x23, 6), (0x4, 5),
    (0x24, 6), (0x5, 5), (0x25, 6), (0x26, 6),
    (0x27, 6), (0x6, 5), (0x74, 7), (0x75, 7),
    (0x28, 6), (0x29, 6), (0x2a, 6), (0x7, 5),
    (0x2b, 6), (0x76, 7), (0x2c, 6), (0x8, 5),
    (0x9, 5), (0x2d, 6), (0x77, 7), (0x78, 7),
    (0x79, 7), (0x7a, 7), (0x7b, 7), (0x7ffe, 15),
    (0x7fc, 11), (0x3ffd, 14), (0x1ffd, 13), (0xffffffc, 28),
    (0xfffe6, 20), (0x3fffd2, 22), (0xfffe7, 20), (0xfffe8, 20),
    (0x3fffd3, 22), (0x3fffd4, 22), (0x3fffd5, 22), (0x7fffd9, 23),
    (0x3fffd6, 22), (0x7fffda, 23), (0x7fffdb, 23), (0x7fffdc, 23),
    (0x7fffdd, 23), (0x7fffde, 23), (0xffffeb, 24), (0x7fffdf, 23),
    (0xffffec, 24), (0xffffed, 24), (0x3fffd7, 22), (0x7fffe0, 23),
    (0xffffee, 24), (0x7fffe1, 23), (0x7fffe2, 23), (0x7fffe3, 23),
    (0x7fffe4, 23), (0x1fffdc, 21), (0x3fffd8, 22), (0x7fffe5, 23),
    (0x3fffd9, 22), (0x7fffe6, 23), (0x7fffe7, 23), (0xffffef, 24),
    (0x3fffda, 22), (0x1fffdd, 21), (0xfffe9, 20), (0x3fffdb, 22),
    (0x3fffdc, 22), (0x7fffe8, 23), (0x7fffe9, 23), (0x1fffde, 21),
    (0x7fffea, 23), (0x3fffdd, 22), (0x3fffde, 22), (0xfffff0, 24),
    (0x1fffdf, 21), (0x3fffdf, 22), (0x7fffeb, 23), (0x7fffec, 23),
    (0x1fffe0, 21), (0x1fffe1, 21), (0x3fffe0, 22), (0x1fffe2, 21),
    (0x7fffed, 23), (0x3fffe1, 22), (0x7fffee, 23), (0x7fffef, 23),
    (0xfffea, 20), (0x3fffe2, 22), (0x3fffe3, 22), (0x3fffe4, 22),
    (0x7ffff0, 23), (0x3fffe5, 22), (0x3fffe6, 22), (0x7ffff1, 23),
    (0x3ffffe0, 26), (0x3ffffe1, 26), (0xfffeb, 20), (0x7fff1, 19),
    (0x3fffe7, 22), (0x7ffff2, 23), (0x3fffe8, 22), (0x1ffffec, 25),
    (0x3ffffe2, 26), (0x3ffffe3, 26), (0x3ffffe4, 26), (0x7ffffde, 27),
    (0x7ffffdf, 27), (0x3ffffe5, 26), (0xfffff1, 24), (0x1ffffed, 25),
    (0x7fff2, 19), (0x1fffe3, 21), (0x3ffffe6, 26), (0x7ffffe0, 27),
    (0x7ffffe1, 27), (0x3ffffe7, 26), (0x7ffffe2, 27), (0xfffff2, 24),
    (0x1fffe4, 21), (0x1fffe5, 21), (0x3ffffe8, 26), (0x3ffffe9, 26),
    (0xffffffd, 28), (0x7ffffe3, 27), (0x7ffffe4, 27), (0x7ffffe5, 27),
    (0xfffec, 20), (0xfffff3, 24), (0xfffed, 20), (0x1fffe6, 21),
    (0x3fffe9, 22), (0x1fffe7, 21), (0x1fffe8, 21), (0x7ffff3, 23),
    (0x3fffea, 22), (0x3fffeb, 22), (0x1ffffee, 25), (0x1ffffef, 25),
    (0xfffff4, 24), (0xfffff5, 24), (0x3ffffea, 26), (0x7ffff4, 23),
    (0x3ffffeb, 26), (0x7ffffe6, 27), (0x3ffffec, 26), (0x3ffffed, 26),
    (0x7ffffe7, 27), (0x7ffffe8, 27), (0x7ffffe9, 27), (0x7ffffea, 27),
    (0x7ffffeb, 27), (0xffffffe, 28), (0x7ffffec, 27), (0x7ffffed, 27),
    (0x7ffffee, 27), (0x7ffffef, 27), (0x7fffff0, 27), (0x3ffffee, 26),
    (0x3fffffff, 30),  # EOS
]
assert len(HUFFMAN_TABLE) == 257

# decode trie: dict keyed by (code_prefix, nbits) is slow; build a flat
# dict code-with-length -> symbol and walk bit by bit
_HUFF_DECODE: dict[tuple[int, int], int] = {
    (code, bits): sym for sym, (code, bits) in enumerate(HUFFMAN_TABLE)
}
_HUFF_MIN_BITS = min(b for _, b in HUFFMAN_TABLE)


def huffman_encode(data: bytes) -> bytes:
    acc = 0
    nbits = 0
    out = bytearray()
    for b in data:
        code, n = HUFFMAN_TABLE[b]
        acc = (acc << n) | code
        nbits += n
        while nbits >= 8:
            nbits -= 8
            out.append((acc >> nbits) & 0xFF)
    if nbits:
        # pad with EOS prefix (all ones)
        out.append(((acc << (8 - nbits)) | ((1 << (8 - nbits)) - 1)) & 0xFF)
    return bytes(out)


def huffman_decode(data: bytes) -> bytes:
    out = bytearray()
    code = 0
    nbits = 0
    for byte in data:
        for shift in range(7, -1, -1):
            code = (code << 1) | ((byte >> shift) & 1)
            nbits += 1
            if nbits < _HUFF_MIN_BITS:
                continue
            sym = _HUFF_DECODE.get((code, nbits))
            if sym is not None:
                if sym == 256:
                    raise ValueError("EOS symbol in huffman data")
                out.append(sym)
                code = 0
                nbits = 0
            elif nbits > 30:
                raise ValueError("invalid huffman code")
    # trailing bits must be a prefix of EOS (all ones), <= 7 bits
    if nbits > 7 or code != (1 << nbits) - 1:
        raise ValueError("bad huffman padding")
    return bytes(out)


# ---- integer / string primitives (RFC 7541 §5) ----------------------------

def encode_int(value: int, prefix_bits: int, first_byte_flags: int = 0) -> bytes:
    limit = (1 << prefix_bits) - 1
    if value < limit:
        return bytes([first_byte_flags | value])
    out = bytearray([first_byte_flags | limit])
    value -= limit
    while value >= 128:
        out.append((value & 0x7F) | 0x80)
        value >>= 7
    out.append(value)
    return bytes(out)


def decode_int(data: bytes, pos: int, prefix_bits: int) -> tuple[int, int]:
    limit = (1 << prefix_bits) - 1
    if pos >= len(data):
        raise ValueError("truncated integer")
    value = data[pos] & limit
    pos += 1
    if value < limit:
        return value, pos
    shift = 0
    while True:
        if pos >= len(data):
            raise ValueError("truncated varint")
        b = data[pos]
        pos += 1
        value += (b & 0x7F) << shift
        shift += 7
        if not (b & 0x80):
            return value, pos
        if shift > 35:
            raise ValueError("integer overflow")


def encode_str(s: str | bytes, huffman: bool = True) -> bytes:
    raw = s.encode("utf-8") if isinstance(s, str) else s
    if huffman:
        enc = huffman_encode(raw)
        if len(enc) < len(raw):
            return encode_int(len(enc), 7, 0x80) + enc
    return encode_int(len(raw), 7, 0x00) + raw


def decode_str(data: bytes, pos: int) -> tuple[bytes, int]:
    if pos >= len(data):
        raise ValueError("truncated string")
    huff = bool(data[pos] & 0x80)
    length, pos = decode_int(data, pos, 7)
    if pos + length > len(data):
        raise ValueError("truncated string body")
    raw = data[pos:pos + length]
    pos += length
    return (huffman_decode(raw) if huff else raw), pos


# ---- dynamic table ---------------------------------------------------------

class _DynTable:
    """FIFO of (name, value); size accounting per RFC 7541 §4.1."""

    def __init__(self, max_size: int = 4096):
        self.entries: list[tuple[str, str]] = []  # newest first
        self.size = 0
        self.max_size = max_size

    @staticmethod
    def entry_size(n: str, v: str) -> int:
        return len(n.encode()) + len(v.encode()) + 32

    def add(self, n: str, v: str) -> None:
        es = self.entry_size(n, v)
        while self.entries and self.size + es > self.max_size:
            on, ov = self.entries.pop()
            self.size -= self.entry_size(on, ov)
        if es <= self.max_size:
            self.entries.insert(0, (n, v))
            self.size += es
        else:
            self.entries.clear()
            self.size = 0

    def resize(self, max_size: int) -> None:
        self.max_size = max_size
        while self.entries and self.size > self.max_size:
            on, ov = self.entries.pop()
            self.size -= self.entry_size(on, ov)


class HpackEncoder:
    def __init__(self, max_table_size: int = 4096, use_huffman: bool = True):
        self._table = _DynTable(max_table_size)
        self._use_huffman = use_huffman
        # mutation counter + repeated-block cache (see encode_cached)
        self._version = 0
        self._cache: dict[tuple, tuple[int, bytes]] = {}

    def set_max_table_size(self, n: int) -> None:
        # peer lowered SETTINGS_HEADER_TABLE_SIZE; a size-update block
        # would be emitted on the next header block in a strict impl — we
        # simply clamp and emit the update eagerly next encode.  The
        # version bump invalidates encode_cached NOW: a cached block
        # replayed after the peer resized would skip the mandatory §6.3
        # size-update prefix and desync both tables.
        self._pending_resize = n
        self._version += 1

    def encode(self, headers: list[tuple[str, str]]) -> bytes:
        out = bytearray()
        pending = getattr(self, "_pending_resize", None)
        if pending is not None:
            self._table.resize(pending)
            self._version += 1
            out += encode_int(pending, 5, 0x20)
            self._pending_resize = None
        for name, value in headers:
            name = name.lower()
            idx = _STATIC_BY_PAIR.get((name, value))
            if idx is None:
                for i, (n, v) in enumerate(self._table.entries):
                    if n == name and v == value:
                        idx = len(STATIC_TABLE) + i + 1
                        break
            if idx is not None:
                out += encode_int(idx, 7, 0x80)  # indexed field
                continue
            name_idx = _STATIC_BY_NAME.get(name)
            if name_idx is None:
                for i, (n, _) in enumerate(self._table.entries):
                    if n == name:
                        name_idx = len(STATIC_TABLE) + i + 1
                        break
            # literal with incremental indexing (01 pattern, 6-bit prefix)
            if name_idx is not None:
                out += encode_int(name_idx, 6, 0x40)
            else:
                out += encode_int(0, 6, 0x40)
                out += encode_str(name, self._use_huffman)
            out += encode_str(value, self._use_huffman)
            self._table.add(name, value)
            self._version += 1
        return bytes(out)

    def encode_cached(self, headers: tuple) -> bytes:
        """Encoded bytes for a REPEATED header tuple.  Unary RPC re-sends
        identical header lists every call; after the first call inserts
        them into the dynamic table, later encodes are pure index bytes
        and deterministic — as long as the table hasn't mutated since.
        Cache entries are keyed on the header tuple and validated against
        the mutation counter; an encode that itself mutates the table is
        never cached (replaying its bytes would double-insert and desync
        the peer's table)."""
        v = self._version
        hit = self._cache.get(headers)
        if hit is not None and hit[0] == v:
            return hit[1]
        out = self.encode(list(headers))
        if self._version == v:
            if len(self._cache) >= 128:
                self._cache.clear()
            self._cache[headers] = (v, out)
        return out


class HpackDecoder:
    def __init__(self, max_table_size: int = 4096):
        self._table = _DynTable(max_table_size)
        self._settings_cap = max_table_size

    def set_max_table_size(self, n: int) -> None:
        self._settings_cap = n
        if self._table.max_size > n:
            self._table.resize(n)

    def _lookup(self, idx: int) -> tuple[str, str]:
        if idx <= 0:
            raise ValueError("index 0")
        if idx <= len(STATIC_TABLE):
            return STATIC_TABLE[idx - 1]
        di = idx - len(STATIC_TABLE) - 1
        if di >= len(self._table.entries):
            raise ValueError(f"dynamic index {idx} out of range")
        return self._table.entries[di]

    def decode(self, data: bytes) -> list[tuple[str, str]]:
        headers: list[tuple[str, str]] = []
        pos = 0
        while pos < len(data):
            b = data[pos]
            if b & 0x80:  # indexed
                idx, pos = decode_int(data, pos, 7)
                headers.append(self._lookup(idx))
            elif b & 0x40:  # literal with incremental indexing
                idx, pos = decode_int(data, pos, 6)
                if idx:
                    name = self._lookup(idx)[0]
                else:
                    nb, pos = decode_str(data, pos)
                    name = nb.decode("utf-8", "replace")
                vb, pos = decode_str(data, pos)
                value = vb.decode("utf-8", "replace")
                self._table.add(name, value)
                headers.append((name, value))
            elif b & 0x20:  # dynamic table size update
                size, pos = decode_int(data, pos, 5)
                if size > self._settings_cap:
                    raise ValueError("table size update beyond settings cap")
                self._table.resize(size)
            else:  # literal without indexing (0000) / never indexed (0001)
                idx, pos = decode_int(data, pos, 4)
                if idx:
                    name = self._lookup(idx)[0]
                else:
                    nb, pos = decode_str(data, pos)
                    name = nb.decode("utf-8", "replace")
                vb, pos = decode_str(data, pos)
                headers.append((name, vb.decode("utf-8", "replace")))
        return headers
