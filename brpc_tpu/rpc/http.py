"""HTTP/1.1 client channel (reference policy/http_rpc_protocol.cpp client
side + details/http_message.*; SURVEY.md §2.4).

The native core frames complete HTTP messages (including chunked bodies) on
client connections exactly like it does server-side, so the client here is
protocol logic only: request serialization, keep-alive connection reuse,
response parsing, and the JSON RESTful bridge (json2pb's http call path —
call any tpu-rpc server's /Service/Method with a JSON body).

For progressive/streaming responses (ProgressiveAttachment server push,
reference progressive_attachment.h) `request_stream` uses a dedicated
connection in raw mode and de-chunks incrementally, delivering data pieces
as they arrive.
"""
from __future__ import annotations

import json
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from brpc_tpu import errors
from brpc_tpu.butil.containers import CaseIgnoredDict
from brpc_tpu.rpc.transport import MSG_RAW, Transport


@dataclass
class HttpResponse:
    status: int = 0
    reason: str = ""
    version: str = "HTTP/1.1"
    # case-insensitive lookup, original casing preserved on iteration
    # (case_ignored_flat_map slot; reference http_header.h)
    headers: CaseIgnoredDict = field(default_factory=CaseIgnoredDict)
    body: bytes = b""

    def json(self):
        return json.loads(self.body)

    @property
    def ok(self) -> bool:
        return 200 <= self.status < 300


def _dechunk(data: bytes) -> bytes:
    out = []
    off = 0
    while True:
        nl = data.find(b"\r\n", off)
        if nl < 0:
            raise ValueError("truncated chunked body")
        size_tok = data[off:nl].split(b";", 1)[0]
        size = int(size_tok, 16)
        off = nl + 2
        if size == 0:
            break
        out.append(data[off : off + size])
        off += size + 2
    return b"".join(out)


def _parse_head(head: bytes) -> HttpResponse:
    lines = head.split(b"\r\n")
    parts = lines[0].decode("latin1").split(" ", 2)
    r = HttpResponse()
    r.version = parts[0]
    r.status = int(parts[1]) if len(parts) > 1 else 0
    r.reason = parts[2] if len(parts) > 2 else ""
    for ln in lines[1:]:
        if not ln:
            continue
        k, _, v = ln.decode("latin1").partition(":")
        r.headers[k.strip()] = v.strip()
    return r


def parse_http_response(raw: bytes) -> HttpResponse:
    head, _, body = raw.partition(b"\r\n\r\n")
    r = _parse_head(head)
    if r.headers.get("transfer-encoding", "").lower().find("chunked") >= 0:
        r.body = _dechunk(body)
    else:
        r.body = body
    return r


def build_request(method: str, path: str, headers: dict | None,
                  body: bytes, host: str) -> bytes:
    hdr = [f"{method} {path} HTTP/1.1", f"Host: {host}"]
    hs = {k.lower(): (k, v) for k, v in (headers or {}).items()}
    if body and "content-length" not in hs:
        hs["content-length"] = ("Content-Length", str(len(body)))
    if "connection" not in hs:
        hs["connection"] = ("Connection", "keep-alive")
    for _, (k, v) in hs.items():
        hdr.append(f"{k}: {v}")
    hdr.append("\r\n")
    return "\r\n".join(hdr).encode("latin1") + body


class HttpChannel:
    """Keep-alive HTTP/1.1 client over the native socket core.

    One multiplexed connection per channel; requests are serialized (HTTP/1.1
    without pipelining — responses come back FIFO and the native executor
    may reorder message callbacks, so one in-flight request at a time).
    Reconnects transparently after peer close/failure.
    """

    def __init__(self, address: str, timeout_ms: int = 2000):
        if address.startswith("http://"):
            address = address[len("http://"):].rstrip("/")
        host, _, port = address.partition(":")
        self.host = host
        self.port = int(port) if port else 80
        self.timeout_s = timeout_ms / 1000.0
        self._sid: Optional[int] = None
        self._mu = threading.Lock()          # serializes requests
        self._resp_event = threading.Event()
        self._resp_raw: Optional[bytes] = None
        # Responses carry no ids in HTTP/1.1; correlate by socket.  A late
        # response or failure from a connection we already abandoned (timed
        # out + closed) must not complete the NEXT request.
        self._expect_sid: Optional[int] = None

    # ---- connection management ----

    def _on_message(self, sid, kind, meta, body) -> None:
        if sid != self._expect_sid:
            return  # stale response from an abandoned connection
        self._resp_raw = body.to_bytes()
        self._resp_event.set()

    def _on_failed(self, sid, err) -> None:
        if self._sid == sid:
            self._sid = None
        if sid == self._expect_sid:
            # unblock the waiter on this connection with an error
            self._resp_event.set()

    def _ensure_conn(self) -> int:
        if self._sid is not None and Transport.instance().alive(self._sid):
            return self._sid
        self._sid = Transport.instance().connect(
            self.host, self.port, self._on_message, self._on_failed)
        return self._sid

    def close(self) -> None:
        if self._sid is not None:
            Transport.instance().close(self._sid)
            self._sid = None

    # ---- requests ----

    def request(self, method: str, path: str, body: bytes | str = b"",
                headers: dict | None = None,
                timeout_s: float | None = None) -> HttpResponse:
        if isinstance(body, str):
            body = body.encode()
        deadline = timeout_s if timeout_s is not None else self.timeout_s
        if method.upper() == "HEAD":
            # HEAD responses carry entity headers (incl. Content-Length)
            # with NO body — the native keep-alive parser would wait for a
            # body that never comes, so use a one-shot raw-mode read.
            return self._head_request(path, headers, deadline)
        raw_req = build_request(method, path, headers, body,
                                f"{self.host}:{self.port}")
        with self._mu:
            try:
                for attempt in range(2):   # one transparent reconnect
                    sid = self._ensure_conn()
                    self._resp_event.clear()
                    self._resp_raw = None
                    self._expect_sid = sid
                    if Transport.instance().write_raw(sid, raw_req) != 0:
                        self._sid = None
                        continue
                    if not self._resp_event.wait(deadline):
                        # timed out: the connection state is unknown, drop it
                        self._expect_sid = None
                        Transport.instance().close(sid)
                        self._sid = None
                        raise errors.RpcError(
                            errors.ERPCTIMEDOUT,
                            f"HTTP {method} {path} timed out")
                    if self._resp_raw is None:
                        # connection failed under us; retry on a fresh one
                        continue
                    r = parse_http_response(self._resp_raw)
                    h = r.headers
                    if ("content-length" not in h
                            and "chunked" not in
                            h.get("transfer-encoding", "").lower()
                            and r.status not in (204, 304)
                            and not (100 <= r.status < 200)):
                        # No framing headers and a status that defaults to
                        # having a body: the body is close-delimited (RFC
                        # 7230 §3.3.3) and the native parser framed only
                        # the headers — fail loudly instead of returning
                        # an empty body.  request_stream() handles these
                        # via raw-mode EOF.  Drop the connection: its
                        # pending body bytes would otherwise poison the
                        # next request on the cached socket.
                        Transport.instance().close(sid)
                        if self._sid == sid:
                            self._sid = None
                        raise errors.RpcError(
                            errors.ERESPONSE,
                            "close-delimited HTTP body unsupported by "
                            "request(); use request_stream()")
                    return r
            finally:
                self._expect_sid = None
        raise errors.RpcError(errors.EFAILEDSOCKET,
                              f"HTTP connection to {self.host}:{self.port} "
                              "failed")

    def _head_request(self, path: str, headers: dict | None,
                      deadline: float) -> HttpResponse:
        reader = self.request_stream("HEAD", path, on_data=None,
                                     headers=headers)
        if not reader.wait(deadline):
            reader.cancel()
            raise errors.RpcError(errors.ERPCTIMEDOUT,
                                  f"HTTP HEAD {path} timed out")
        if reader.error is not None or reader.response is None:
            raise errors.RpcError(errors.ERESPONSE,
                                  f"HEAD failed: {reader.error}")
        return reader.response

    def get(self, path: str, **kw) -> HttpResponse:
        return self.request("GET", path, **kw)

    def post(self, path: str, body: bytes | str = b"", **kw) -> HttpResponse:
        return self.request("POST", path, body=body, **kw)

    # ---- the RESTful RPC bridge (json2pb http call path) ----

    def call(self, service: str, method: str, payload,
             timeout_s: float | None = None):
        """POST /Service/Method with a JSON body against a tpu-rpc server;
        returns the decoded JSON response or raises RpcError with the
        server-reported code."""
        r = self.post(f"/{service}/{method}", json.dumps(payload),
                      headers={"Content-Type": "application/json"},
                      timeout_s=timeout_s)
        if not r.ok:
            try:
                err = r.json()
                raise errors.RpcError(int(err.get("error", errors.EINTERNAL)),
                                      err.get("text", ""))
            except (ValueError, KeyError):
                raise errors.RpcError(errors.EINTERNAL,
                                      f"HTTP {r.status}: {r.body[:200]!r}")
        return r.json() if r.body else None

    # ---- streaming (progressive attachment reader) ----

    def request_stream(self, method: str, path: str,
                       on_data: Callable[[bytes], None],
                       on_end: Callable[[], None] | None = None,
                       headers: dict | None = None,
                       body: bytes = b"") -> "HttpStreamReader":
        """Issue a request on a DEDICATED raw-mode connection and deliver the
        response body incrementally (chunk by chunk for chunked transfer)."""
        reader = HttpStreamReader(on_data, on_end,
                                  head_mode=method.upper() == "HEAD")
        sid = Transport.instance().connect(
            self.host, self.port, reader._on_raw, reader._on_failed)
        Transport.instance().set_protocol(sid, MSG_RAW)
        reader._sid = sid
        raw_req = build_request(method, path, headers, body,
                                f"{self.host}:{self.port}")
        Transport.instance().write_raw(sid, raw_req)
        return reader


class HttpStreamReader:
    """Incremental HTTP response reader over a raw-mode socket: parses the
    status line + headers, then delivers body data as it arrives (de-chunked
    when the transfer is chunked)."""

    def __init__(self, on_data, on_end, head_mode: bool = False):
        self._on_data = on_data
        self._on_end = on_end
        self._head_mode = head_mode
        self._sid: Optional[int] = None
        self._buf = b""
        self._state = "headers"     # headers | chunked | length | eof_body
        self._remaining = 0         # bytes left in current chunk / body
        self._done = threading.Event()
        self.response: Optional[HttpResponse] = None
        # Set when the stream ended abnormally (malformed framing); wait()
        # still returns, callers must check .error for truncation.
        self.error: Optional[str] = None

    def wait(self, timeout_s: float | None = None) -> bool:
        return self._done.wait(timeout_s)

    def cancel(self) -> None:
        if self._sid is not None:
            Transport.instance().close(self._sid)

    # ---- internal ----

    def _finish(self) -> None:
        if not self._done.is_set():
            self._done.set()
            if self._on_end is not None:
                self._on_end()
            if self._sid is not None:
                Transport.instance().close(self._sid)

    def _on_failed(self, sid, err) -> None:
        # EOF delimits the body in eof_body mode; anywhere else a drop
        # before completion is a truncation the caller must see.
        if self._state == "eof_body":
            if self._buf:
                self._emit(self._buf)
                self._buf = b""
        elif not self._done.is_set():
            self.error = f"connection dropped mid-{self._state} (err={err})"
        self._finish()

    def _emit(self, data: bytes) -> None:
        if data and self._on_data is not None:
            self._on_data(data)

    def _on_raw(self, sid, kind, meta, body) -> None:
        self._buf += body.to_bytes()
        try:
            self._pump()
        except Exception as e:
            self.error = f"{type(e).__name__}: {e}"
            self._finish()

    def _pump(self) -> None:
        if self._state == "headers":
            pos = self._buf.find(b"\r\n\r\n")
            if pos < 0:
                return
            head = self._buf[: pos + 4]
            self._buf = self._buf[pos + 4:]
            self.response = _parse_head(head)
            h = self.response.headers
            if self._head_mode or self.response.status in (204, 304) \
                    or 100 <= self.response.status < 200:
                self._finish()
                return
            if "chunked" in h.get("transfer-encoding", "").lower():
                self._state = "chunked"
            elif "content-length" in h:
                self._state = "length"
                self._remaining = int(h["content-length"])
            else:
                self._state = "eof_body"
        if self._state == "length":
            take = min(len(self._buf), self._remaining)
            if take:
                self._emit(self._buf[:take])
                self._buf = self._buf[take:]
                self._remaining -= take
            if self._remaining == 0:
                self._finish()
            return
        if self._state == "eof_body":
            if self._buf:
                self._emit(self._buf)
                self._buf = b""
            return
        while self._state == "chunked":
            if self._remaining > 0:
                take = min(len(self._buf), self._remaining)
                self._emit(self._buf[:take])
                self._buf = self._buf[take:]
                self._remaining -= take
                if self._remaining == 0:
                    # swallow the trailing CRLF
                    self._remaining = -2
                if not self._buf:
                    return
            if self._remaining == -2:
                # skip the CRLF after chunk data (may arrive split)
                if len(self._buf) < 2:
                    return
                self._buf = self._buf[2:]
                self._remaining = 0
            nl = self._buf.find(b"\r\n")
            if nl < 0:
                return
            size = int(self._buf[:nl].split(b";", 1)[0], 16)
            self._buf = self._buf[nl + 2:]
            if size == 0:
                self._finish()
                return
            self._remaining = size
