"""Memcache binary protocol — pipelined client + server-side handlers.

Reference: policy/memcache_binary_protocol.cpp (parse/pack),
memcache.cpp:806 (MemcacheRequest/Response command builders; the reference
is client-only — we add a server-side service so in-process loopback tests
work, mirroring how RedisService does, redis.h:192).

Wire format (24-byte header, network order):
  magic(1) opcode(1) keylen(2) extraslen(1) datatype(1) vbucket|status(2)
  totalbody(4) opaque(4) cas(8)
The native core frames one complete packet per message (MSG_MEMCACHE,
src/cc/net/parser.cc:parse_memcache) and delivers packets INLINE in
per-connection FIFO order — binary memcache has no reordering, so client
reply matching is a deque pop exactly like redis pipelining
(PipelinedInfo, socket.h:159).
"""
from __future__ import annotations

import struct
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Callable, Optional

from brpc_tpu import errors
from brpc_tpu.rpc.transport import MSG_MEMCACHE, Transport

HEADER = struct.Struct(">BBHBBHIIQ")
MAGIC_REQ = 0x80
MAGIC_RES = 0x81

# opcodes
OP_GET = 0x00
OP_SET = 0x01
OP_ADD = 0x02
OP_REPLACE = 0x03
OP_DELETE = 0x04
OP_INCR = 0x05
OP_DECR = 0x06
OP_FLUSH = 0x08
OP_NOOP = 0x0A
OP_VERSION = 0x0B
OP_APPEND = 0x0E
OP_PREPEND = 0x0F
OP_TOUCH = 0x1C

# status codes
ST_OK = 0x0000
ST_KEY_ENOENT = 0x0001
ST_KEY_EEXISTS = 0x0002
ST_E2BIG = 0x0003
ST_EINVAL = 0x0004
ST_NOT_STORED = 0x0005
ST_DELTA_BADVAL = 0x0006
ST_UNKNOWN_COMMAND = 0x0081

_STATUS_TEXT = {
    ST_KEY_ENOENT: "key not found",
    ST_KEY_EEXISTS: "key exists (cas mismatch)",
    ST_E2BIG: "value too large",
    ST_EINVAL: "invalid arguments",
    ST_NOT_STORED: "item not stored",
    ST_DELTA_BADVAL: "non-numeric value for incr/decr",
    ST_UNKNOWN_COMMAND: "unknown command",
}


class MemcacheError(Exception):
    def __init__(self, status: int, msg: str = ""):
        self.status = status
        super().__init__(msg or _STATUS_TEXT.get(status,
                                                 f"status 0x{status:04x}"))


def pack_packet(magic: int, opcode: int, key: bytes = b"",
                extras: bytes = b"", value: bytes = b"", status: int = 0,
                opaque: int = 0, cas: int = 0) -> bytes:
    total = len(extras) + len(key) + len(value)
    return HEADER.pack(magic, opcode, len(key), len(extras), 0, status,
                       total, opaque, cas) + extras + key + value


class Packet:
    __slots__ = ("magic", "opcode", "status", "opaque", "cas", "extras",
                 "key", "value")

    @classmethod
    def parse(cls, data: bytes) -> "Packet":
        if len(data) < 24:
            raise ValueError("short memcache packet")
        (magic, opcode, keylen, extraslen, _dt, status, total, opaque,
         cas) = HEADER.unpack_from(data)
        if len(data) < 24 + total:
            raise ValueError("truncated memcache packet")
        # bounded decode: extras/key lengths are wire-controlled — when
        # they exceed the body the slices below mis-split silently
        # (extras swallows the value) instead of refusing the packet
        if extraslen + keylen > total:
            raise ValueError("memcache header lengths exceed body")
        p = cls()
        p.magic, p.opcode, p.status, p.opaque, p.cas = \
            magic, opcode, status, opaque, cas
        body = data[24:24 + total]
        p.extras = body[:extraslen]
        p.key = body[extraslen:extraslen + keylen]
        p.value = body[extraslen + keylen:]
        return p


class GetResult:
    __slots__ = ("value", "flags", "cas")

    def __init__(self, value: bytes, flags: int, cas: int):
        self.value = value
        self.flags = flags
        self.cas = cas

    def __repr__(self):
        return f"GetResult(value={self.value!r}, flags={self.flags}, " \
               f"cas={self.cas})"


class MemcacheChannel:
    """Pipelined memcache binary client (reference memcache.cpp command
    surface: Get/Set/Add/Replace/Append/Prepend/Delete/Flush/Incr/Decr/
    Touch/Version, memcache.h:40-130)."""

    def __init__(self, address: str, timeout_ms: int = 1000):
        host, _, port = address.rpartition(":")
        self.host, self.port = host or "127.0.0.1", int(port)
        self.timeout_ms = timeout_ms
        self._mu = threading.Lock()
        self._sid: Optional[int] = None
        self._pending: deque[tuple[Future, int]] = deque()  # (fut, opcode)

    # ---- connection ----

    def _ensure_connected(self) -> int:
        with self._mu:
            t = Transport.instance()
            if self._sid is not None and t.alive(self._sid):
                return self._sid
            self._fail_pending_locked(errors.EFAILEDSOCKET)
            self._sid = t.connect(self.host, self.port, self._on_message,
                                  self._on_failed)
            return self._sid

    def _fail_pending_locked(self, code: int) -> None:
        while self._pending:
            fut, _ = self._pending.popleft()
            if not fut.done():
                fut.set_exception(errors.RpcError(code, "memcache conn lost"))

    def _on_failed(self, sid: int, err: int) -> None:
        with self._mu:
            if sid == self._sid:
                self._sid = None
            self._fail_pending_locked(errors.EFAILEDSOCKET)

    def _on_message(self, sid: int, kind: int, meta: bytes, body) -> None:
        if kind != MSG_MEMCACHE:
            return
        try:
            p = Packet.parse(body.to_bytes())
        except ValueError:
            return
        with self._mu:
            fut = self._pending.popleft()[0] if self._pending else None
        if fut is not None and not fut.done():
            fut.set_result(p)

    # ---- raw pipelined op ----

    def execute(self, opcode: int, key: bytes | str = b"",
                extras: bytes = b"", value: bytes = b"",
                cas: int = 0) -> Future:
        if isinstance(key, str):
            key = key.encode()
        sid = self._ensure_connected()
        fut: Future = Future()
        pkt = pack_packet(MAGIC_REQ, opcode, key, extras, value, cas=cas)
        with self._mu:
            self._pending.append((fut, opcode))
        if Transport.instance().write_raw(sid, pkt) != 0:
            with self._mu:
                # remove by identity — a concurrent append may sit behind
                # us, and leaving our entry would shift FIFO matching by
                # one for every later caller
                try:
                    self._pending.remove((fut, opcode))
                except ValueError:
                    pass
            if not fut.done():   # _on_failed may have beaten us to it
                fut.set_exception(errors.RpcError(errors.EFAILEDSOCKET,
                                                  "memcache write failed"))
        return fut

    def _wait(self, fut: Future, timeout_ms: Optional[int]) -> Packet:
        try:
            return fut.result((timeout_ms or self.timeout_ms) / 1e3)
        except TimeoutError:
            raise errors.RpcError(errors.ERPCTIMEDOUT, "memcache timed out")

    # ---- command surface ----

    def get(self, key, timeout_ms=None) -> Optional[GetResult]:
        p = self._wait(self.execute(OP_GET, key), timeout_ms)
        if p.status == ST_KEY_ENOENT:
            return None
        if p.status != ST_OK:
            raise MemcacheError(p.status, p.value.decode("utf-8", "replace"))
        flags = struct.unpack(">I", p.extras[:4])[0] if len(p.extras) >= 4 \
            else 0
        return GetResult(p.value, flags, p.cas)

    def _store(self, opcode, key, value, flags, exptime, cas,
               timeout_ms) -> int:
        if isinstance(value, str):
            value = value.encode()
        extras = struct.pack(">II", flags, exptime)
        p = self._wait(self.execute(opcode, key, extras, value, cas=cas),
                       timeout_ms)
        if p.status != ST_OK:
            raise MemcacheError(p.status, p.value.decode("utf-8", "replace"))
        return p.cas

    def set(self, key, value, flags=0, exptime=0, cas=0, timeout_ms=None):
        return self._store(OP_SET, key, value, flags, exptime, cas,
                           timeout_ms)

    def add(self, key, value, flags=0, exptime=0, timeout_ms=None):
        return self._store(OP_ADD, key, value, flags, exptime, 0, timeout_ms)

    def replace(self, key, value, flags=0, exptime=0, timeout_ms=None):
        return self._store(OP_REPLACE, key, value, flags, exptime, 0,
                           timeout_ms)

    def _concat(self, opcode, key, value, timeout_ms) -> None:
        if isinstance(value, str):
            value = value.encode()
        p = self._wait(self.execute(opcode, key, b"", value), timeout_ms)
        if p.status != ST_OK:
            raise MemcacheError(p.status)

    def append(self, key, value, timeout_ms=None) -> None:
        self._concat(OP_APPEND, key, value, timeout_ms)

    def prepend(self, key, value, timeout_ms=None) -> None:
        self._concat(OP_PREPEND, key, value, timeout_ms)

    def delete(self, key, timeout_ms=None) -> bool:
        p = self._wait(self.execute(OP_DELETE, key), timeout_ms)
        if p.status == ST_KEY_ENOENT:
            return False
        if p.status != ST_OK:
            raise MemcacheError(p.status)
        return True

    def _arith(self, opcode, key, delta, initial, exptime,
               timeout_ms) -> int:
        extras = struct.pack(">QQI", delta, initial, exptime)
        p = self._wait(self.execute(opcode, key, extras), timeout_ms)
        if p.status != ST_OK:
            raise MemcacheError(p.status)
        return struct.unpack(">Q", p.value[:8])[0]

    def incr(self, key, delta=1, initial=0, exptime=0, timeout_ms=None):
        return self._arith(OP_INCR, key, delta, initial, exptime, timeout_ms)

    def decr(self, key, delta=1, initial=0, exptime=0, timeout_ms=None):
        return self._arith(OP_DECR, key, delta, initial, exptime, timeout_ms)

    def touch(self, key, exptime, timeout_ms=None) -> bool:
        extras = struct.pack(">I", exptime)
        p = self._wait(self.execute(OP_TOUCH, key, extras), timeout_ms)
        return p.status == ST_OK

    def version(self, timeout_ms=None) -> str:
        p = self._wait(self.execute(OP_VERSION), timeout_ms)
        return p.value.decode()

    def flush_all(self, timeout_ms=None) -> None:
        p = self._wait(self.execute(OP_FLUSH), timeout_ms)
        if p.status != ST_OK:
            raise MemcacheError(p.status)

    def noop(self, timeout_ms=None) -> None:
        self._wait(self.execute(OP_NOOP), timeout_ms)

    def close(self) -> None:
        # release _mu before the native close: the failed-callback fires
        # synchronously on this thread and takes _mu (redis.py pattern)
        with self._mu:
            sid, self._sid = self._sid, None
        if sid is not None:
            Transport.instance().close(sid)


# ---- server side ----------------------------------------------------------

class MemcacheService:
    """Server-side memcache-speaking service: override handle_packet or use
    MemoryMemcacheService.  Wired via ServerOptions.memcache_service; the
    Server answers MSG_MEMCACHE frames with handle_bytes()."""

    def handle_bytes(self, raw: bytes) -> bytes:
        try:
            req = Packet.parse(raw)
        except ValueError:
            return pack_packet(MAGIC_RES, 0, status=ST_EINVAL)
        return self.handle_packet(req)

    def handle_packet(self, req: Packet) -> bytes:  # pragma: no cover
        return pack_packet(MAGIC_RES, req.opcode, status=ST_UNKNOWN_COMMAND,
                           opaque=req.opaque)


class MemoryMemcacheService(MemcacheService):
    """In-memory store speaking the full binary command set (loopback
    integration tests + demos; plays the role memcached does in the
    reference's example/memcache_c++)."""

    VERSION = b"tpu-rpc-memcache/1.0"

    def __init__(self):
        self._mu = threading.Lock()
        # key -> [value, flags, cas, expire_ts(0=never)]
        self._store: dict[bytes, list] = {}
        self._cas = 0

    def _next_cas(self) -> int:
        self._cas += 1
        return self._cas

    def _alive(self, ent) -> bool:
        return ent[3] == 0 or ent[3] > time.time()

    def _get(self, key):
        ent = self._store.get(key)
        if ent is None or not self._alive(ent):
            self._store.pop(key, None)
            return None
        return ent

    @staticmethod
    def _exptime_to_ts(exptime: int) -> float:
        if exptime == 0:
            return 0
        # memcache semantics: >30 days means absolute unix time
        return exptime if exptime > 2592000 else time.time() + exptime

    def handle_packet(self, req: Packet) -> bytes:
        op = req.opcode
        oq = req.opaque

        def resp(status=ST_OK, extras=b"", value=b"", cas=0):
            return pack_packet(MAGIC_RES, op, b"", extras, value,
                               status=status, opaque=oq, cas=cas)

        with self._mu:
            if op == OP_GET:
                ent = self._get(req.key)
                if ent is None:
                    return resp(ST_KEY_ENOENT)
                return resp(extras=struct.pack(">I", ent[1]), value=ent[0],
                            cas=ent[2])
            if op in (OP_SET, OP_ADD, OP_REPLACE):
                flags, exptime = struct.unpack(">II", req.extras[:8]) \
                    if len(req.extras) >= 8 else (0, 0)
                ent = self._get(req.key)
                if op == OP_ADD and ent is not None:
                    return resp(ST_KEY_EEXISTS)
                if op == OP_REPLACE and ent is None:
                    return resp(ST_KEY_ENOENT)
                if req.cas and (ent is None or ent[2] != req.cas):
                    return resp(ST_KEY_EEXISTS)
                cas = self._next_cas()
                self._store[req.key] = [req.value, flags, cas,
                                        self._exptime_to_ts(exptime)]
                return resp(cas=cas)
            if op in (OP_APPEND, OP_PREPEND):
                ent = self._get(req.key)
                if ent is None:
                    return resp(ST_NOT_STORED)
                ent[0] = ent[0] + req.value if op == OP_APPEND \
                    else req.value + ent[0]
                ent[2] = self._next_cas()
                return resp(cas=ent[2])
            if op == OP_DELETE:
                ent = self._get(req.key)
                if ent is None:
                    return resp(ST_KEY_ENOENT)
                del self._store[req.key]
                return resp()
            if op in (OP_INCR, OP_DECR):
                if len(req.extras) < 20:
                    return resp(ST_EINVAL)
                delta, initial, exptime = struct.unpack(">QQI",
                                                        req.extras[:20])
                ent = self._get(req.key)
                if ent is None:
                    if exptime == 0xFFFFFFFF:
                        return resp(ST_KEY_ENOENT)
                    n = initial
                else:
                    try:
                        n = int(ent[0])
                    except ValueError:
                        return resp(ST_DELTA_BADVAL)
                    n = n + delta if op == OP_INCR else max(0, n - delta)
                cas = self._next_cas()
                self._store[req.key] = [str(n).encode(),
                                        ent[1] if ent else 0, cas,
                                        ent[3] if ent
                                        else self._exptime_to_ts(exptime)]
                return resp(value=struct.pack(">Q", n), cas=cas)
            if op == OP_TOUCH:
                ent = self._get(req.key)
                if ent is None:
                    return resp(ST_KEY_ENOENT)
                exptime = struct.unpack(">I", req.extras[:4])[0] \
                    if len(req.extras) >= 4 else 0
                ent[3] = self._exptime_to_ts(exptime)
                return resp()
            if op == OP_FLUSH:
                self._store.clear()
                return resp()
            if op == OP_VERSION:
                return resp(value=self.VERSION)
            if op == OP_NOOP:
                return resp()
        return resp(ST_UNKNOWN_COMMAND)
