"""RpcMeta — the per-frame protocol metadata codec.

Role of baidu_rpc_meta.proto in the reference (RpcMeta{request,response,
compress_type,correlation_id,attachment_size,stream_settings,user_fields},
baidu_rpc_meta.proto:26-36).  Our wire meta is a fixed little header plus
TLV fields, hand-packed with struct — no protobuf dependency in the framing
path, and the body/attachment ride after the meta unserialized (zero-copy
slot for tensor payloads, like baidu_std's attachment).

Layout (after the 16-byte TRPC frame header handled natively):
  u8 version | u8 msg_type | u16 flags | u64 correlation_id | u16 attempt
  then TLV: u8 tag | u32 len | bytes
"""
from __future__ import annotations

import struct
from dataclasses import dataclass, field

MSG_REQUEST = 0
MSG_RESPONSE = 1
# streaming frames (§5.7) share the meta codec
MSG_STREAM_DATA = 2
MSG_STREAM_FEEDBACK = 3
MSG_STREAM_CLOSE = 4

# flags bits (u16 in the fixed header)
# rpcz head-sampling is decided ONCE at the trace root and inherited by
# every span of the trace; this bit carries the decision across the wire
# alongside T_TRACE_ID so a cascaded server keeps (or drops) the WHOLE
# trace instead of re-rolling per hop
FLAG_TRACE_SAMPLED = 0x0001

_FIXED = struct.Struct("<BBHQH")

# TLV tags
T_SERVICE = 1
T_METHOD = 2
T_ERROR_CODE = 3
T_ERROR_TEXT = 4
T_COMPRESS = 5
T_ATTACHMENT_SIZE = 6
T_TIMEOUT_MS = 7
T_TRACE_ID = 8
T_SPAN_ID = 9
T_PARENT_SPAN_ID = 10
T_USER_FIELD = 11
T_CONTENT_TYPE = 12
T_STREAM_ID = 13
T_STREAM_OFFSET = 14
T_TENSOR_HEADER = 15
T_AUTH = 16
T_STREAM_SEQ = 17

COMPRESS_NONE = 0
COMPRESS_GZIP = 1
COMPRESS_ZLIB = 2
COMPRESS_SNAPPY = 3  # native block-format codec (src/cc/butil/snappy.cc)
COMPRESS_ZSTD = 4

# Transport-reserved user-field keys (the rail ticket/source and the
# stream buffer exchange ride user_fields; caller-supplied fields must
# never collide).  brpc_tpu.ici.rail aliases the first two.
F_TICKET = "icit"
F_SRC_DEV = "icisrc"
F_SBUF = "sbuf"
# stream tensor-rail advertisement: the device id this side of a stream
# can RECEIVE tensor payloads on (StreamSettings exchange)
F_SDEV = "sdev"
RESERVED_USER_FIELD_KEYS = frozenset({F_TICKET, F_SRC_DEV, F_SBUF, F_SDEV})


def normalize_user_fields(fields: dict) -> dict:
    """ONE validation/normalization for caller-supplied user fields, both
    directions: keys must be str without NULs (a NUL corrupts the
    key\\0value TLV framing; bytes keys would be sent as reprs) and must
    not be transport-reserved; bytes values pass through, everything else
    is str()ed."""
    out = {}
    for k, v in (fields or {}).items():
        if not isinstance(k, str) or "\x00" in k:
            raise ValueError(
                f"user_fields key {k!r} must be a str without NUL bytes")
        if k in RESERVED_USER_FIELD_KEYS:
            raise ValueError(
                f"user_fields key {k!r} is reserved by the transport")
        out[k] = v if isinstance(v, (bytes, bytearray)) else str(v)
    return out


def strip_reserved_user_fields(fields: dict) -> dict:
    """Drop transport keys before surfacing received fields to callers."""
    return {k: v for k, v in (fields or {}).items()
            if k not in RESERVED_USER_FIELD_KEYS}


# constant byte prefixes of the stream-DATA fast encoder (all arguments
# are literals; packing them per message was pure waste on the hot path)
_STREAM_DATA_HDR = (_FIXED.pack(1, MSG_STREAM_DATA, 0, 0, 0)
                    + struct.pack("<BI", T_STREAM_ID, 8))
_STREAM_SEQ_TL = struct.pack("<BI", T_STREAM_SEQ, 8)
_TICKET_KEY = F_TICKET.encode() + b"\x00"
_SRC_DEV_KEY = F_SRC_DEV.encode() + b"\x00"


@dataclass(slots=True)
class RpcMeta:
    msg_type: int = MSG_REQUEST
    correlation_id: int = 0
    attempt: int = 0
    flags: int = 0
    service: str = ""
    method: str = ""
    error_code: int = 0
    error_text: str = ""
    compress_type: int = COMPRESS_NONE
    attachment_size: int = 0
    timeout_ms: int = 0
    trace_id: int = 0
    span_id: int = 0
    parent_span_id: int = 0
    content_type: str = ""
    stream_id: int = 0
    stream_offset: int = 0
    stream_seq: int = 0
    tensor_header: bytes = b""
    auth: bytes = b""
    user_fields: dict = field(default_factory=dict)

    def encode(self) -> bytes:
        parts = [_FIXED.pack(1, self.msg_type, self.flags,
                             self.correlation_id, self.attempt)]

        def tlv(tag: int, payload: bytes):
            parts.append(struct.pack("<BI", tag, len(payload)))
            parts.append(payload)

        if self.service:
            tlv(T_SERVICE, self.service.encode())
        if self.method:
            tlv(T_METHOD, self.method.encode())
        if self.error_code:
            tlv(T_ERROR_CODE, struct.pack("<i", self.error_code))
        if self.error_text:
            tlv(T_ERROR_TEXT, self.error_text.encode())
        if self.compress_type:
            tlv(T_COMPRESS, bytes([self.compress_type]))
        if self.attachment_size:
            tlv(T_ATTACHMENT_SIZE, struct.pack("<Q", self.attachment_size))
        if self.timeout_ms:
            tlv(T_TIMEOUT_MS, struct.pack("<I", self.timeout_ms))
        if self.trace_id:
            tlv(T_TRACE_ID, struct.pack("<Q", self.trace_id))
        if self.span_id:
            tlv(T_SPAN_ID, struct.pack("<Q", self.span_id))
        if self.parent_span_id:
            tlv(T_PARENT_SPAN_ID, struct.pack("<Q", self.parent_span_id))
        if self.content_type:
            tlv(T_CONTENT_TYPE, self.content_type.encode())
        if self.stream_id:
            tlv(T_STREAM_ID, struct.pack("<Q", self.stream_id))
        if self.stream_offset:
            tlv(T_STREAM_OFFSET, struct.pack("<Q", self.stream_offset))
        if self.stream_seq:
            tlv(T_STREAM_SEQ, struct.pack("<Q", self.stream_seq))
        if self.tensor_header:
            tlv(T_TENSOR_HEADER, self.tensor_header)
        if self.auth:
            tlv(T_AUTH, self.auth)
        for k, v in self.user_fields.items():
            if isinstance(v, str):
                v = v.encode()
            tlv(T_USER_FIELD, k.encode() + b"\x00" + v)
        return b"".join(parts)

    @staticmethod
    def encode_stream_data(stream_id: int, seq: int,
                           ticket: str | None = None,
                           src_dev: str | None = None) -> bytes:
        """Direct encoder for the stream-DATA hot shape (the only meta a
        busy tensor stream produces, thousands per second): identical
        bytes to RpcMeta(msg_type=MSG_STREAM_DATA, stream_id=..,
        stream_seq=..) with the rail user fields, without the dataclass
        construction and 17-branch generic encode (measured ~26% of
        per-message stream cost; equality pinned by
        test_encode_stream_data_fast_path_identical)."""
        parts = [_STREAM_DATA_HDR,
                 struct.pack("<Q", stream_id)]
        if seq:
            parts.append(_STREAM_SEQ_TL)
            parts.append(struct.pack("<Q", seq))
        if ticket is not None:
            p = _TICKET_KEY + ticket.encode()
            parts.append(struct.pack("<BI", T_USER_FIELD, len(p)))
            parts.append(p)
        if src_dev is not None:
            p = _SRC_DEV_KEY + src_dev.encode()
            parts.append(struct.pack("<BI", T_USER_FIELD, len(p)))
            parts.append(p)
        return b"".join(parts)

    @classmethod
    def decode(cls, data: bytes) -> "RpcMeta":
        if len(data) < _FIXED.size:
            raise ValueError("meta too short")
        ver, msg_type, flags, cid, attempt = _FIXED.unpack_from(data, 0)
        if ver != 1:
            raise ValueError(f"unknown meta version {ver}")
        m = cls(msg_type=msg_type, correlation_id=cid, attempt=attempt,
                flags=flags)
        off = _FIXED.size
        n = len(data)
        while off + 5 <= n:
            tag, ln = struct.unpack_from("<BI", data, off)
            off += 5
            if off + ln > n:
                raise ValueError("truncated TLV")
            p = data[off : off + ln]
            off += ln
            if tag == T_SERVICE:
                m.service = p.decode()
            elif tag == T_METHOD:
                m.method = p.decode()
            elif tag == T_ERROR_CODE:
                m.error_code = struct.unpack("<i", p)[0]
            elif tag == T_ERROR_TEXT:
                m.error_text = p.decode()
            elif tag == T_COMPRESS:
                m.compress_type = p[0]
            elif tag == T_ATTACHMENT_SIZE:
                m.attachment_size = struct.unpack("<Q", p)[0]
            elif tag == T_TIMEOUT_MS:
                m.timeout_ms = struct.unpack("<I", p)[0]
            elif tag == T_TRACE_ID:
                m.trace_id = struct.unpack("<Q", p)[0]
            elif tag == T_SPAN_ID:
                m.span_id = struct.unpack("<Q", p)[0]
            elif tag == T_PARENT_SPAN_ID:
                m.parent_span_id = struct.unpack("<Q", p)[0]
            elif tag == T_CONTENT_TYPE:
                m.content_type = p.decode()
            elif tag == T_STREAM_ID:
                m.stream_id = struct.unpack("<Q", p)[0]
            elif tag == T_STREAM_OFFSET:
                m.stream_offset = struct.unpack("<Q", p)[0]
            elif tag == T_STREAM_SEQ:
                m.stream_seq = struct.unpack("<Q", p)[0]
            elif tag == T_TENSOR_HEADER:
                m.tensor_header = p
            elif tag == T_AUTH:
                m.auth = p
            elif tag == T_USER_FIELD:
                k, _, v = p.partition(b"\x00")
                m.user_fields[k.decode()] = v
            # unknown tags skipped (forward compat)
        return m
