"""Mongo wire protocol — server-side adaptor (OP_MSG / OP_QUERY), minimal
BSON codec, and a small client for loopback tests.

Reference: policy/mongo_protocol.cpp:298 (server-side OP_QUERY handling),
mongo_head.h (16-byte LE header {messageLength, requestID, responseTo,
opCode}), mongo_service_adaptor.h.  The native core frames one complete
mongo message per MSG_MONGO (src/cc/net/parser.cc:parse_mongo, whole
message incl. header in body).

BSON support covers the types a command router needs: double, string,
embedded document, array, binary, bool, null, int32, int64.  This is a
clean-room subset of the BSON spec — no external bson dependency.
"""
from __future__ import annotations

import struct
import threading
from concurrent.futures import Future
from typing import Any, Callable, Optional

from brpc_tpu import errors
from brpc_tpu.rpc.transport import MSG_MONGO, Transport

OP_REPLY = 1
OP_QUERY = 2004
OP_MSG = 2013

HEADER = struct.Struct("<iiii")  # messageLength, requestID, responseTo, opCode


# ---- BSON ------------------------------------------------------------------

def bson_encode(doc: dict) -> bytes:
    out = bytearray(4)
    for k, v in doc.items():
        key = k.encode() if isinstance(k, str) else bytes(k)
        if isinstance(v, bool):           # before int: bool is an int subtype
            out += b"\x08" + key + b"\x00" + (b"\x01" if v else b"\x00")
        elif isinstance(v, float):
            out += b"\x01" + key + b"\x00" + struct.pack("<d", v)
        elif isinstance(v, int):
            if -(1 << 31) <= v < (1 << 31):
                out += b"\x10" + key + b"\x00" + struct.pack("<i", v)
            else:
                out += b"\x12" + key + b"\x00" + struct.pack("<q", v)
        elif isinstance(v, str):
            raw = v.encode()
            out += b"\x02" + key + b"\x00" + \
                struct.pack("<i", len(raw) + 1) + raw + b"\x00"
        elif isinstance(v, (bytes, bytearray)):
            out += b"\x05" + key + b"\x00" + \
                struct.pack("<i", len(v)) + b"\x00" + bytes(v)
        elif isinstance(v, dict):
            out += b"\x03" + key + b"\x00" + bson_encode(v)
        elif isinstance(v, (list, tuple)):
            out += b"\x04" + key + b"\x00" + \
                bson_encode({str(i): e for i, e in enumerate(v)})
        elif v is None:
            out += b"\x0a" + key + b"\x00"
        else:
            raise TypeError(f"cannot BSON-encode {type(v)!r}")
    out += b"\x00"
    struct.pack_into("<i", out, 0, len(out))
    return bytes(out)


def _bson_cstring(data: bytes, pos: int) -> tuple[str, int]:
    end = data.index(b"\x00", pos)
    return data[pos:end].decode("utf-8", "replace"), end + 1


def bson_decode(data: bytes, pos: int = 0) -> tuple[dict, int]:
    """Returns (doc, next_pos)."""
    if pos + 4 > len(data):
        raise ValueError("truncated bson")
    size = struct.unpack_from("<i", data, pos)[0]
    if size < 5 or pos + size > len(data):
        raise ValueError("bad bson size")
    end = pos + size
    p = pos + 4
    doc: dict = {}
    while p < end - 1:
        etype = data[p]
        p += 1
        key, p = _bson_cstring(data, p)
        if etype == 0x01:
            doc[key] = struct.unpack_from("<d", data, p)[0]
            p += 8
        elif etype == 0x02:
            n = struct.unpack_from("<i", data, p)[0]
            # bounded decode: n is wire-controlled and SIGNED — a
            # negative n walks p backwards (infinite loop), an oversize
            # one silently short-reads past the doc
            if n < 1 or p + 4 + n > end:
                raise ValueError("bad bson string length")
            doc[key] = data[p + 4:p + 4 + n - 1].decode("utf-8", "replace")
            p += 4 + n
        elif etype == 0x03:
            doc[key], p = bson_decode(data, p)
        elif etype == 0x04:
            sub, p = bson_decode(data, p)
            doc[key] = [sub[k] for k in sorted(sub, key=int)]
        elif etype == 0x05:
            n = struct.unpack_from("<i", data, p)[0]
            if n < 0 or p + 5 + n > end:
                raise ValueError("bad bson binary length")
            doc[key] = data[p + 5:p + 5 + n]
            p += 5 + n
        elif etype == 0x08:
            doc[key] = bool(data[p])
            p += 1
        elif etype == 0x09:  # UTC datetime as int64 millis
            doc[key] = struct.unpack_from("<q", data, p)[0]
            p += 8
        elif etype == 0x0A:
            doc[key] = None
        elif etype == 0x10:
            doc[key] = struct.unpack_from("<i", data, p)[0]
            p += 4
        elif etype == 0x12:
            doc[key] = struct.unpack_from("<q", data, p)[0]
            p += 8
        else:
            raise ValueError(f"unsupported bson type 0x{etype:02x}")
    if data[end - 1] != 0:
        raise ValueError("bson doc missing terminator")
    return doc, end


# ---- wire messages ---------------------------------------------------------

def build_op_msg(doc: dict, request_id: int, response_to: int = 0) -> bytes:
    body = struct.pack("<I", 0) + b"\x00" + bson_encode(doc)
    return HEADER.pack(16 + len(body), request_id, response_to, OP_MSG) + body


def build_op_reply(docs: list[dict], request_id: int,
                   response_to: int) -> bytes:
    body = struct.pack("<iqii", 0, 0, 0, len(docs)) + \
        b"".join(bson_encode(d) for d in docs)
    return HEADER.pack(16 + len(body), request_id, response_to,
                       OP_REPLY) + body


class MongoService:
    """Server-side command router (the mongo_service_adaptor.h slot).
    Commands dispatch on the FIRST key of the command document (OP_MSG
    semantics; OP_QUERY against <db>.$cmd routes the same way).

        svc = MongoService()

        @svc.command("ping")
        def ping(doc):
            return {"ok": 1}

    Wired via ServerOptions.mongo_service."""

    def __init__(self):
        self._commands: dict[str, Callable] = {}
        self._reply_id = 0
        self._mu = threading.Lock()
        for name in ("ping", "ismaster", "hello", "buildinfo"):
            self._commands[name] = self._default_ok

    def _default_ok(self, doc: dict) -> dict:
        return {"ok": 1, "ismaster": True, "maxWireVersion": 6,
                "minWireVersion": 0}

    def command(self, name: str):
        def deco(fn):
            self._commands[name.lower()] = fn
            return fn
        return deco

    def add_handler(self, name: str, fn: Callable) -> None:
        self._commands[name.lower()] = fn

    def _next_id(self) -> int:
        with self._mu:
            self._reply_id += 1
            return self._reply_id

    def _run(self, doc: dict) -> dict:
        if not doc:
            return {"ok": 0, "errmsg": "empty command", "code": 59}
        cmd = next(iter(doc)).lower()
        fn = self._commands.get(cmd)
        if fn is None:
            return {"ok": 0, "errmsg": f"no such command: '{cmd}'",
                    "code": 59}
        try:
            out = fn(doc)
            if "ok" not in out:
                out["ok"] = 1
            return out
        except Exception as e:
            return {"ok": 0, "errmsg": f"{type(e).__name__}: {e}",
                    "code": 8}

    def handle_bytes(self, raw: bytes) -> bytes:
        if len(raw) < 16:
            return b""
        _, request_id, _, opcode = HEADER.unpack_from(raw)
        try:
            if opcode == OP_MSG:
                # flagBits u32 + section kind 0 doc (kind-1 sequences are
                # rejected like an unsupported command)
                kind = raw[20]
                if kind != 0:
                    out = {"ok": 0, "errmsg": "unsupported section kind",
                           "code": 59}
                else:
                    doc, _ = bson_decode(raw, 21)
                    out = self._run(doc)
                return build_op_msg(out, self._next_id(), request_id)
            if opcode == OP_QUERY:
                pos = 16 + 4  # header + flags
                coll, pos = _bson_cstring(raw, pos)
                pos += 8  # numberToSkip + numberToReturn
                doc, _ = bson_decode(raw, pos)
                out = self._run(doc)
                return build_op_reply([out], self._next_id(), request_id)
        except (ValueError, IndexError, struct.error) as e:
            # truncated headers raise IndexError, truncated BSON elements
            # raise struct.error — all must yield the error reply, not a
            # swallowed exception and a silently hung client.  Reply in the
            # request's own dialect: OP_QUERY speakers can't parse OP_MSG.
            err = {"ok": 0, "errmsg": f"bad message: {e}", "code": 22}
            if opcode == OP_QUERY:
                return build_op_reply([err], self._next_id(), request_id)
            return build_op_msg(err, self._next_id(), request_id)
        return b""  # unknown opcode: drop (connection stays up)


class MongoClient:
    """Minimal OP_MSG command client for loopback tests/demos (the
    reference has no mongo client; this exists so the adaptor is testable
    in-process, SURVEY.md §4 pattern)."""

    def __init__(self, address: str, timeout_ms: int = 2000):
        host, _, port = address.rpartition(":")
        self.host, self.port = host or "127.0.0.1", int(port)
        self.timeout_ms = timeout_ms
        self._mu = threading.Lock()
        self._sid: Optional[int] = None
        self._req = 0
        self._pending: dict[int, Future] = {}

    def _ensure_connected(self) -> int:
        with self._mu:
            t = Transport.instance()
            if self._sid is not None and t.alive(self._sid):
                return self._sid
            self._fail_pending_locked()
            self._sid = t.connect(self.host, self.port, self._on_message,
                                  self._on_failed)
            t.set_protocol(self._sid, MSG_MONGO)
            return self._sid

    def _fail_pending_locked(self) -> None:
        pend, self._pending = self._pending, {}
        for fut in pend.values():
            if not fut.done():
                fut.set_exception(errors.RpcError(errors.EFAILEDSOCKET,
                                                  "mongo conn lost"))

    def _on_failed(self, sid: int, err: int) -> None:
        with self._mu:
            if sid == self._sid:
                self._sid = None
            self._fail_pending_locked()

    def _on_message(self, sid: int, kind: int, meta: bytes, body) -> None:
        raw = body.to_bytes()
        if len(raw) < 16:
            return
        _, _, response_to, opcode = HEADER.unpack_from(raw)
        try:
            if opcode == OP_MSG:
                doc, _ = bson_decode(raw, 21)
            elif opcode == OP_REPLY:
                doc, _ = bson_decode(raw, 16 + 20)
            else:
                return
        except (ValueError, IndexError, struct.error):
            return
        with self._mu:
            fut = self._pending.pop(response_to, None)
        if fut is not None and not fut.done():
            fut.set_result(doc)

    def command(self, doc: dict, timeout_ms: Optional[int] = None) -> dict:
        sid = self._ensure_connected()
        fut: Future = Future()
        with self._mu:
            self._req += 1
            rid = self._req
            self._pending[rid] = fut
        if Transport.instance().write_raw(sid, build_op_msg(doc, rid)) != 0:
            with self._mu:
                self._pending.pop(rid, None)
            raise errors.RpcError(errors.EFAILEDSOCKET, "mongo write failed")
        try:
            return fut.result((timeout_ms or self.timeout_ms) / 1e3)
        except TimeoutError:
            raise errors.RpcError(errors.ERPCTIMEDOUT,
                                  "mongo command timed out")

    def ping(self) -> bool:
        return self.command({"ping": 1}).get("ok") == 1

    def close(self) -> None:
        with self._mu:
            sid, self._sid = self._sid, None
        if sid is not None:
            Transport.instance().close(sid)
