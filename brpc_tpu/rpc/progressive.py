"""ProgressiveAttachment — server push after the response headers
(reference progressive_attachment.{h,cpp}: chunked HTTP responses written
after `done`, used for long downloads / server-sent streams).

HTTP side: a console/RESTful handler returns a ProgressiveResponse; the
router sends `Transfer-Encoding: chunked` headers and invokes the writer
callback with a ProgressiveAttachment whose write()/close() emit chunks —
from any thread, any time after the handler returned.

TRPC side: the equivalent capability is a Stream riding the RPC
(stream_accept + stream.write), which adds credit-window flow control on
top; see brpc_tpu/rpc/stream.py.
"""
from __future__ import annotations

import threading
from typing import Callable, Optional

from brpc_tpu.rpc.transport import Transport


class ProgressiveAttachment:
    def __init__(self, sid: int):
        self._sid = sid
        self._mu = threading.Lock()
        self._closed = False

    def write(self, data: bytes | str) -> int:
        """Emit one chunk; returns 0 on success, -1 once closed/failed."""
        if isinstance(data, str):
            data = data.encode()
        if not data:
            return 0
        with self._mu:
            if self._closed:
                return -1
            frame = b"%x\r\n%s\r\n" % (len(data), data)
            rc = Transport.instance().write_raw(self._sid, frame)
            if rc != 0:
                self._closed = True
                return -1
            return 0

    def close(self) -> None:
        """Terminate the chunked body (last-chunk marker)."""
        with self._mu:
            if self._closed:
                return
            self._closed = True
            Transport.instance().write_raw(self._sid, b"0\r\n\r\n")

    @property
    def closed(self) -> bool:
        return self._closed

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class ProgressiveResponse:
    """Returned by an HTTP handler to switch the connection into chunked
    mode.  `writer(pa)` runs on the handler's thread; it may hand `pa` to
    another thread and return immediately — chunks can flow afterwards."""

    def __init__(self, writer: Callable[[ProgressiveAttachment], None],
                 content_type: str = "application/octet-stream",
                 status: int = 200,
                 extra_headers: Optional[dict] = None):
        self.writer = writer
        self.content_type = content_type
        self.status = status
        self.extra_headers = extra_headers or {}
