"""Redis protocol — RESP codec, pipelined client, server-side handlers.

Reference: policy/redis_protocol.cpp (parse/pack), redis_command.cpp /
redis_reply.cpp (codec), redis.h:192,214 (RedisService/RedisCommandHandler
— build a redis-speaking server), PipelinedInfo (socket.h:159 — client
pipelining with FIFO reply matching).

The native core frames one complete RESP value per message (MSG_REDIS,
src/cc/net/parser.cc) and delivers redis messages INLINE on the socket's
dispatcher thread: RESP has no correlation ids, so per-connection FIFO
order is the protocol contract (see Socket::DispatchMessages).  That makes
client reply matching a simple deque pop, and server replies naturally
ride out in command order — keep server handlers fast for the same reason.

Python value ↔ RESP mapping:
  reply encode: str → simple string, bytes → bulk, int → integer,
                None → null bulk, list/tuple → array, RedisError → error
  reply decode: + → str, $ → bytes, : → int, $-1/*-1 → None, * → list,
                - → RedisError instance (raised by call(), returned raw
                by execute() futures via .result())
"""
from __future__ import annotations

import threading
from collections import deque
from concurrent.futures import Future
from typing import Callable, Optional

from brpc_tpu import errors
from brpc_tpu.rpc.transport import MSG_REDIS, Transport

CRLF = b"\r\n"


class RedisError(Exception):
    """An -ERR style reply."""


# ---- codec ---------------------------------------------------------------

def encode_command(*args) -> bytes:
    """RESP array of bulk strings (redis_command.cpp analog)."""
    if not args:
        raise ValueError("empty command")
    parts = [b"*%d\r\n" % len(args)]
    for a in args:
        if isinstance(a, str):
            a = a.encode()
        elif isinstance(a, (int, float)):
            a = str(a).encode()
        elif not isinstance(a, (bytes, bytearray)):
            raise TypeError(f"bad command arg type {type(a)!r}")
        parts.append(b"$%d\r\n" % len(a))
        parts.append(bytes(a))
        parts.append(CRLF)
    return b"".join(parts)


def encode_reply(value) -> bytes:
    """Python value → RESP reply bytes (server side)."""
    if value is None:
        return b"$-1\r\n"
    if isinstance(value, RedisError):
        text = str(value).replace("\r", " ").replace("\n", " ")
        return b"-" + text.encode() + CRLF
    if isinstance(value, bool):
        return b":%d\r\n" % int(value)
    if isinstance(value, int):
        return b":%d\r\n" % value
    if isinstance(value, str):
        if "\r" in value or "\n" in value:
            b = value.encode()
            return b"$%d\r\n" % len(b) + b + CRLF
        return b"+" + value.encode() + CRLF
    if isinstance(value, (bytes, bytearray)):
        return b"$%d\r\n" % len(value) + bytes(value) + CRLF
    if isinstance(value, (list, tuple)):
        return b"*%d\r\n" % len(value) + b"".join(encode_reply(v) for v in value)
    raise TypeError(f"cannot encode reply of type {type(value)!r}")


def parse_value(data: bytes, off: int = 0):
    """Parse one RESP value; returns (value, next_off).

    The native parser guarantees completeness, so truncation here is a
    protocol error rather than a wait-for-more condition."""
    nl = data.index(b"\r\n", off)
    line = data[off:nl]
    off = nl + 2
    t = line[:1]
    if t == b"+":
        return line[1:].decode(errors="replace"), off
    if t == b"-":
        return RedisError(line[1:].decode(errors="replace")), off
    if t == b":":
        return int(line[1:]), off
    if t == b"$":
        n = int(line[1:])
        if n < 0:
            return None, off
        body = data[off : off + n]
        if len(body) != n or data[off + n : off + n + 2] != CRLF:
            raise ValueError("truncated bulk string")
        return bytes(body), off + n + 2
    if t == b"*":
        n = int(line[1:])
        if n < 0:
            return None, off
        out = []
        for _ in range(n):
            v, off = parse_value(data, off)
            out.append(v)
        return out, off
    raise ValueError(f"bad RESP type byte {t!r}")


# ---- client --------------------------------------------------------------

class RedisChannel:
    """Pipelined redis client over the native socket core.

    Every execute() appends a Future to the pending deque and writes the
    command under one lock, so reply matching is strict FIFO — the same
    invariant PipelinedInfo maintains in the reference (socket.h:159)."""

    def __init__(self, address: str, timeout_ms: int = 1000,
                 password: Optional[str] = None):
        host, _, port = address.rpartition(":")
        self._addr = (host or "127.0.0.1", int(port))
        self.timeout_ms = timeout_ms
        self._mu = threading.Lock()
        self._pending: deque[Future] = deque()
        self._sid: Optional[int] = None
        self._password = password

    # connection is lazy so a channel can be created before the server is up
    def _ensure_connected(self) -> int:
        with self._mu:
            if self._sid is not None and Transport.instance().alive(self._sid):
                return self._sid
            # connection died: fail anything still pending on it
            self._fail_pending_locked(errors.EFAILEDSOCKET)
            sid = Transport.instance().connect(
                self._addr[0], self._addr[1],
                on_message=self._on_message, on_failed=self._on_failed)
            if sid == 0:
                raise errors.RpcError(errors.ECONNREFUSED,
                                      f"connect {self._addr} failed")
            self._sid = sid
            if self._password is not None:
                f = Future()
                self._pending.append(f)
                Transport.instance().write_raw(
                    sid, encode_command("AUTH", self._password))
            return sid

    def _fail_pending_locked(self, code: int) -> None:
        while self._pending:
            f = self._pending.popleft()
            if not f.done():
                f.set_exception(errors.RpcError(code, "connection failed"))

    def _on_failed(self, sid: int, err: int) -> None:
        with self._mu:
            if sid == self._sid:
                self._sid = None
            self._fail_pending_locked(errors.EFAILEDSOCKET)

    def _on_message(self, sid: int, kind: int, meta: bytes, body) -> None:
        if kind != MSG_REDIS:
            return
        try:
            value, _ = parse_value(body.to_bytes())
        except Exception as e:
            value = RedisError(f"bad reply: {e}")
        with self._mu:
            f = self._pending.popleft() if self._pending else None
        if f is not None and not f.done():
            f.set_result(value)

    def execute(self, *args) -> Future:
        """Issue one command; returns a Future of the decoded reply.
        RedisError replies resolve the future (not raise) so pipelines can
        inspect per-command errors."""
        sid = self._ensure_connected()
        cmd = encode_command(*args)
        with self._mu:
            f = Future()
            self._pending.append(f)
            rc = Transport.instance().write_raw(sid, cmd)
            if rc != 0:
                self._pending.pop()
                f.set_exception(
                    errors.RpcError(errors.EFAILEDSOCKET, "write failed"))
        return f

    def call(self, *args, timeout_ms: Optional[int] = None):
        """Synchronous command; raises RedisError on -ERR replies."""
        f = self.execute(*args)
        t = (timeout_ms if timeout_ms is not None else self.timeout_ms) / 1e3
        try:
            value = f.result(timeout=t)
        except TimeoutError:
            raise errors.RpcError(errors.ERPCTIMEDOUT,
                                  f"redis call timed out after {t}s")
        if isinstance(value, RedisError):
            raise value
        return value

    def pipeline(self) -> "RedisPipeline":
        return RedisPipeline(self)

    def close(self) -> None:
        with self._mu:
            sid, self._sid = self._sid, None
        if sid is not None:
            Transport.instance().close(sid)


class RedisPipeline:
    """Batch many commands into one write; results() waits for all."""

    def __init__(self, channel: RedisChannel):
        self._ch = channel
        self._cmds: list[bytes] = []
        self._futures: list[Future] = []

    def execute(self, *args) -> "RedisPipeline":
        self._cmds.append(encode_command(*args))
        return self

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if exc[0] is None:
            self.flush()

    def flush(self) -> list[Future]:
        if not self._cmds:
            return self._futures
        ch = self._ch
        sid = ch._ensure_connected()
        with ch._mu:
            for _ in self._cmds:
                f = Future()
                ch._pending.append(f)
                self._futures.append(f)
            rc = Transport.instance().write_raw(sid, b"".join(self._cmds))
            if rc != 0:
                for f in self._futures:
                    if not f.done():
                        ch._pending.remove(f)
                        f.set_exception(errors.RpcError(
                            errors.EFAILEDSOCKET, "write failed"))
        self._cmds.clear()
        return self._futures

    def results(self, timeout_ms: Optional[int] = None) -> list:
        self.flush()
        t = (timeout_ms if timeout_ms is not None else self._ch.timeout_ms) / 1e3
        return [f.result(timeout=t) for f in self._futures]


# ---- server --------------------------------------------------------------

class RedisService:
    """Server-side command dispatch (reference RedisService/
    RedisCommandHandler, redis.h:192,214).

    Handlers take (cntl-less) `fn(args: list[bytes]) -> value` and return a
    Python value encoded by encode_reply; raise RedisError for -ERR replies.
    Handlers run inline on the socket's dispatcher thread (that's what keeps
    replies in command order) — keep them fast and non-blocking."""

    def __init__(self):
        self._handlers: dict[str, Callable] = {}

    def command(self, name: str):
        def deco(fn):
            self._handlers[name.upper()] = fn
            return fn
        return deco

    def add_handler(self, name: str, fn: Callable) -> None:
        self._handlers[name.upper()] = fn

    def handle_bytes(self, raw: bytes) -> bytes:
        """One complete RESP command in, one RESP reply out."""
        try:
            cmd, _ = parse_value(raw)
        except Exception as e:
            return encode_reply(RedisError(f"ERR protocol error: {e}"))
        if not isinstance(cmd, list) or not cmd:
            return encode_reply(RedisError("ERR expected command array"))
        name = (cmd[0].decode(errors="replace")
                if isinstance(cmd[0], (bytes, bytearray)) else str(cmd[0]))
        fn = self._handlers.get(name.upper())
        if fn is None:
            return encode_reply(
                RedisError(f"ERR unknown command '{name}'"))
        try:
            return encode_reply(fn(cmd[1:]))
        except RedisError as e:
            return encode_reply(e)
        except Exception as e:  # handler bug — surface as error reply
            return encode_reply(RedisError(f"ERR internal: {e}"))


class MemoryRedisService(RedisService):
    """A small in-memory redis: GET/SET/DEL/EXISTS/INCR/DECR/MGET/MSET/
    KEYS/PING/ECHO/FLUSHDB — enough for tests, demos, and as a template for
    real redis-speaking services (reference example/redis_c++/redis_server).
    """

    def __init__(self):
        super().__init__()
        self._data: dict[bytes, bytes] = {}
        self._mu = threading.Lock()
        r = self.add_handler
        r("PING", lambda a: "PONG" if not a else bytes(a[0]))
        r("ECHO", lambda a: bytes(a[0]))
        r("SET", self._set)
        r("GET", self._get)
        r("DEL", self._del)
        r("EXISTS", self._exists)
        r("INCR", lambda a: self._incrby(a[0], 1))
        r("DECR", lambda a: self._incrby(a[0], -1))
        r("INCRBY", lambda a: self._incrby(a[0], int(a[1])))
        r("MGET", self._mget)
        r("MSET", self._mset)
        r("KEYS", self._keys)
        r("FLUSHDB", self._flush)

    def _set(self, a):
        with self._mu:
            self._data[bytes(a[0])] = bytes(a[1])
        return "OK"

    def _get(self, a):
        with self._mu:
            return self._data.get(bytes(a[0]))

    def _del(self, a):
        n = 0
        with self._mu:
            for k in a:
                n += self._data.pop(bytes(k), None) is not None
        return n

    def _exists(self, a):
        with self._mu:
            return sum(bytes(k) in self._data for k in a)

    def _incrby(self, key, delta):
        key = bytes(key)
        with self._mu:
            try:
                v = int(self._data.get(key, b"0")) + delta
            except ValueError:
                raise RedisError("ERR value is not an integer")
            self._data[key] = str(v).encode()
            return v

    def _mget(self, a):
        with self._mu:
            return [self._data.get(bytes(k)) for k in a]

    def _mset(self, a):
        if len(a) % 2:
            raise RedisError("ERR wrong number of arguments for MSET")
        with self._mu:
            for i in range(0, len(a), 2):
                self._data[bytes(a[i])] = bytes(a[i + 1])
        return "OK"

    def _keys(self, a):
        import fnmatch
        pat = bytes(a[0]) if a else b"*"
        with self._mu:
            return [k for k in self._data
                    if fnmatch.fnmatchcase(k.decode(errors="replace"),
                                           pat.decode(errors="replace"))]

    def _flush(self, a):
        with self._mu:
            self._data.clear()
        return "OK"
