"""rpc_dump — sampled capture of live requests to recordio files
(reference rpc_dump.{h,cpp}:50-69; replayed by tools/rpc_replay, §5.5).

Enable with flags (live-editable through /flags):
  rpc_dump            — master switch
  rpc_dump_dir        — output directory (one file per process)
  rpc_dump_ratio      — sample 1/N requests (1 = every request)
  rpc_dump_max_files  — rotation depth
  rpc_dump_max_requests_in_one_file — rotation threshold

Each record: meta = the request's wire RpcMeta bytes, body = the request
payload (still compressed/serialized exactly as received) — what's needed
to re-issue the call byte-for-byte.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Optional

from brpc_tpu import flags
from brpc_tpu.butil.recordio import RecordWriter

flags.define_flag("rpc_dump", False, "sample incoming requests to recordio files")
flags.define_flag("rpc_dump_dir", "./rpc_dump", "directory for dump files")
flags.define_flag("rpc_dump_ratio", 1, "sample one of every N requests")
flags.define_flag("rpc_dump_max_files", 5, "max rotated dump files kept")
flags.define_flag("rpc_dump_max_requests_in_one_file", 10000,
             "rotate after this many records")


class RpcDumper:
    _instance: Optional["RpcDumper"] = None
    _instance_lock = threading.Lock()

    @classmethod
    def instance(cls) -> "RpcDumper":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def __init__(self):
        self._mu = threading.Lock()
        self._counter = 0
        self._in_file = 0
        self._fp = None
        self._writer: Optional[RecordWriter] = None
        self._files: list[str] = []

    def sample(self, meta_bytes: bytes, body: bytes) -> None:
        """Called per request from the server dispatch path.  Cheap when
        disabled (one flag read); when enabled, the record is handed to
        the shared bvar Collector and the file IO runs on its background
        thread, not here (the reference's rpc_dump rides
        bvar::Collector the same way, rpc_dump.h:50-69)."""
        if not flags.get_flag("rpc_dump"):
            return
        with self._mu:
            self._counter += 1
            ratio = max(1, int(flags.get_flag("rpc_dump_ratio")))
            if self._counter % ratio != 0:
                return
        # Consult the speed limit BEFORE materializing the record: a
        # denied sample must cost nothing — bytes() copies of a large
        # body on the dispatch thread are exactly the overhead the
        # collector handoff exists to avoid.
        from brpc_tpu.bvar.collector import Collector, get_or_create_limit
        if not get_or_create_limit("rpc_dump", 1000).grab():
            return
        Collector.instance().submit(_DumpSample(self, meta_bytes, body),
                                    family="rpc_dump")

    def _write_sample(self, meta_bytes: bytes, body: bytes) -> None:
        with self._mu:
            try:
                self._write_locked(meta_bytes, body)
            except OSError:
                pass  # dumping must never break serving

    def _write_locked(self, meta_bytes: bytes, body: bytes) -> None:
        limit = int(flags.get_flag("rpc_dump_max_requests_in_one_file"))
        if self._writer is None or self._in_file >= limit:
            self._rotate_locked()
        self._writer.write(body, meta_bytes)
        self._writer.flush()
        self._in_file += 1

    def _rotate_locked(self) -> None:
        if self._fp is not None:
            self._fp.close()
        d = str(flags.get_flag("rpc_dump_dir"))
        os.makedirs(d, exist_ok=True)
        path = os.path.join(
            d, f"requests.{int(time.time())}.{os.getpid()}."
               f"{len(self._files)}.rdump")
        self._fp = open(path, "wb")
        self._writer = RecordWriter(self._fp)
        self._in_file = 0
        self._files.append(path)
        max_files = int(flags.get_flag("rpc_dump_max_files"))
        while len(self._files) > max_files:
            old = self._files.pop(0)
            try:
                os.remove(old)
            except OSError:
                pass

    def close(self) -> None:
        # drain records still queued on the collector before closing
        from brpc_tpu.bvar.collector import Collector
        Collector.instance().flush(family="rpc_dump")
        with self._mu:
            if self._fp is not None:
                self._fp.close()
                self._fp = None
                self._writer = None


class _DumpSample:
    """Collected record: writes on the collector thread."""

    __slots__ = ("dumper", "meta", "body")

    def __init__(self, dumper: "RpcDumper", meta: bytes, body: bytes):
        self.dumper = dumper
        self.meta = bytes(meta)
        self.body = bytes(body)

    def dump_and_destroy(self) -> None:
        self.dumper._write_sample(self.meta, self.body)
