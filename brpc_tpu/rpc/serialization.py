"""Body serializers + compression registry.

The reference bridges protobuf to other encodings via json2pb/mcpack2pb and a
compression registry (SURVEY.md §2.4).  Our registry covers the payload types
a TPU service actually exchanges:

  raw     opaque bytes (the attachment slot of baidu_std)
  json    dict/list/str/num via JSON
  pb      protobuf Message (class supplied per method)
  tensor  numpy / jax arrays: dtype+shape header in meta, raw device-ready
          bytes as body — the zero-copy slot (no pickle, bounded trust)
  pickle  arbitrary python (explicitly opt-in; server must enable)

Compression (reference compress.cpp registry + gzip/snappy policies,
global.cpp:393-403): gzip, zlib, snappy (native block-format codec,
src/cc/butil/snappy.cc), zstd.
"""
from __future__ import annotations

import gzip as _gzip
import io
import json
import struct
import zlib as _zlib
from typing import Any

import numpy as np

from brpc_tpu.bvar import Adder
from brpc_tpu.rpc import meta as M

# Host-materialization counters: every tensor body that becomes host bytes
# is counted, so the ICI rail's zero-host-copy claim is testable
# (ici/rail.py host_copy_count).
tensor_host_encodes = Adder("tensor_host_encodes")
tensor_host_decodes = Adder("tensor_host_decodes")

try:
    import zstandard as _zstd
except Exception:  # pragma: no cover
    _zstd = None


# ---- serializers ----

class Serializer:
    name = "raw"

    def encode(self, obj: Any) -> tuple[bytes, bytes]:
        """Returns (body, tensor_header)."""
        raise NotImplementedError

    def decode(self, body: bytes, tensor_header: bytes) -> Any:
        raise NotImplementedError


def as_bytes(x):
    """THE zero-copy boundary rule, in one place: IOBuf-backed memoryviews
    stay views through transport slicing (attachment split, decompress
    pass-through) and materialize to bytes exactly here, where handler
    code takes over and expects real bytes (.decode(), dict keys,
    concatenation)."""
    return bytes(x) if isinstance(x, memoryview) else x


class RawSerializer(Serializer):
    name = "raw"

    def encode(self, obj):
        if isinstance(obj, (bytes, bytearray, memoryview)):
            return bytes(obj), b""
        if obj is None:
            return b"", b""
        raise TypeError(f"raw serializer needs bytes, got {type(obj)}")

    def decode(self, body, tensor_header):
        # the tensor serializer consumes views with NO copy; every
        # bytes-contract serializer materializes via as_bytes
        return as_bytes(body)


class JsonSerializer(Serializer):
    name = "json"

    def encode(self, obj):
        return json.dumps(obj, separators=(",", ":")).encode(), b""

    def decode(self, body, tensor_header):
        body = as_bytes(body)
        return json.loads(body) if body else None


class PbSerializer(Serializer):
    """Protobuf messages; the concrete class comes from the method spec."""

    name = "pb"

    def __init__(self, message_class=None):
        self.message_class = message_class

    def encode(self, obj):
        return obj.SerializeToString(), b""

    def decode(self, body, tensor_header):
        body = as_bytes(body)
        if self.message_class is None:
            return body
        msg = self.message_class()
        msg.ParseFromString(body)
        return msg


class PbMessagePool:
    """Pooled protobuf request messages (reference RpcPBMessageFactory,
    rpc_pb_message_factory.{h,cpp}: arena-pooled Get/Return around each
    call).  Messages are Clear()ed on return and reused, cutting the
    per-request allocation for large message types.

    Contract (same as the reference): the framework owns the request
    message; a handler that stashes it past `done` must copy it first.
    Pooling is opt-in per server (ServerOptions.pb_message_pooling).
    """

    MAX_PER_CLASS = 64

    def __init__(self):
        import threading
        self._mu = threading.Lock()
        self._free: dict[type, list] = {}
        self.reused = Adder("pb_pool_reused")
        self.created = Adder("pb_pool_created")

    def get(self, message_class):
        with self._mu:
            lst = self._free.get(message_class)
            if lst:
                self.reused.add(1)
                return lst.pop()
        self.created.add(1)
        return message_class()

    def give_back(self, msg) -> None:
        msg.Clear()
        cls = type(msg)
        with self._mu:
            lst = self._free.setdefault(cls, [])
            if len(lst) < self.MAX_PER_CLASS:
                lst.append(msg)


pb_message_pool = PbMessagePool()


class TensorSerializer(Serializer):
    """ndarray <-> raw bytes + header.  Lists/tuples of arrays supported.

    Header: u8 count, then per tensor: u8 dtype_len, dtype str, u8 ndim,
    ndim*u64 shape.  Bodies are concatenated C-order bytes — importable into
    device buffers without a copy (jax.numpy.frombuffer / device_put).
    """

    name = "tensor"

    def encode(self, obj):
        arrays = obj if isinstance(obj, (list, tuple)) else [obj]
        hdr = [struct.pack("<B", len(arrays))]
        bodies = []
        tensor_host_encodes.add(1)
        for a in arrays:
            a = np.asarray(a)
            dt = a.dtype.str.encode()
            hdr.append(struct.pack("<B", len(dt)) + dt)
            hdr.append(struct.pack("<B", a.ndim) +
                       struct.pack(f"<{a.ndim}Q", *a.shape))
            bodies.append(np.ascontiguousarray(a).tobytes())
        single = not isinstance(obj, (list, tuple))
        flag = b"\x01" if single else b"\x00"
        return b"".join(bodies), flag + b"".join(hdr)

    def decode(self, body, tensor_header):
        if not tensor_header:
            return body
        tensor_host_decodes.add(1)
        try:
            return self._decode_checked(body, tensor_header)
        except IndexError as e:
            # walking past a truncated header is bad INPUT, not a bug:
            # every malformed-header path raises ValueError (the contract
            # callers like the DCN envelope rely on for clean EREQUEST)
            raise ValueError(f"truncated tensor header: {e}")

    def _decode_checked(self, body, tensor_header):
        single = tensor_header[0] == 1
        off = 1
        count = tensor_header[off]
        off += 1
        out = []
        pos = 0
        for _ in range(count):
            dlen = tensor_header[off]
            off += 1
            try:
                dt = np.dtype(
                    tensor_header[off : off + dlen].decode("ascii"))
            except ValueError:
                raise              # already the contract's error family
            except Exception as e:
                # malformed header = bad input, not a programming error.
                # Catch BROADLY: np.dtype ast-parses some spec strings
                # and can raise SyntaxError (found by the decode fuzz
                # target), TypeError, UnicodeDecodeError, ...
                raise ValueError(f"bad dtype in tensor header: {e}")
            off += dlen
            ndim = tensor_header[off]
            off += 1
            try:
                shape = struct.unpack_from(f"<{ndim}Q", tensor_header, off)
            except struct.error as e:
                raise ValueError(f"truncated tensor header: {e}")
            off += 8 * ndim
            # exact Python-int element count (np.prod silently wraps), then
            # bound against the actual body: a hostile header must raise
            # ValueError, not drive numpy into OverflowError/overallocation
            if dt.itemsize == 0:
                # V0/U0/S0: cnt * 0 == 0 would pass the body bound below
                # while a huge cnt still overflows frombuffer's ssize_t
                raise ValueError(f"zero-itemsize dtype {dt} in header")
            cnt = 1
            for d in shape:
                cnt *= int(d)
            if cnt * dt.itemsize > len(body) - pos:
                raise ValueError(
                    f"tensor header claims {cnt} x {dt} at offset {pos} "
                    f"but body has {len(body) - pos} bytes")
            arr = np.frombuffer(body, dtype=dt, count=cnt, offset=pos)
            out.append(arr.reshape(shape))
            pos += cnt * dt.itemsize
        return out[0] if single and out else out


# EXACT (module, name) pairs a pickled payload may reference — the
# globals that builtin containers/scalars and numpy arrays actually emit
# (enumerated with pickletools against this numpy).  pickle.loads on
# peer bytes is arbitrary code execution by design (__reduce__ ->
# os.system); module-prefix wildcards cannot work either: numpy itself
# ships exec gadgets (numpy.testing...runstring is literally exec), and
# dotted STACK_GLOBAL names resolve via attribute traversal so
# "builtins", "eval.__call__" slips any name-based deny list — both
# bypasses live-proven in review.  Deployments that truly trust their
# peers can flip rpc_pickle_unrestricted.
_PICKLE_SAFE = {
    ("builtins", "bytearray"), ("builtins", "complex"),
    ("builtins", "set"), ("builtins", "frozenset"),
    ("collections", "OrderedDict"),
    ("numpy", "dtype"), ("numpy", "ndarray"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "scalar"),
    ("numpy.core.multiarray", "_reconstruct"),   # numpy 1.x payloads
    ("numpy.core.multiarray", "scalar"),
}


from brpc_tpu.flags import define_flag as _define_flag

_define_flag("rpc_pickle_unrestricted", False,
             "allow pickle payloads to reference ANY class (arbitrary "
             "code execution for whoever can reach the port; only for "
             "fully trusted peers)", reloadable=False)


class PickleSerializer(Serializer):
    name = "pickle"

    def encode(self, obj):
        import pickle
        return pickle.dumps(obj), b""

    def decode(self, body, tensor_header):
        import io
        import pickle

        from brpc_tpu import flags
        if flags.get_flag("rpc_pickle_unrestricted", False):
            return pickle.loads(body)
        return _RestrictedUnpickler(io.BytesIO(body)).load()


import pickle as _pickle  # noqa: E402


class _RestrictedUnpickler(_pickle.Unpickler):
    def find_class(self, module, name):
        # dotted names resolve via attribute traversal in CPython's
        # find_class ("eval.__call__" under an allowed module) — reject
        # them outright; legitimate payload globals are plain names
        if "." not in name:
            if (module, name) in _PICKLE_SAFE:
                return super().find_class(module, name)
            # numpy 2 pickles some dtype instances through their
            # numpy.dtypes.<X>DType classes — a closed, data-only family
            if (module == "numpy.dtypes" and name.endswith("DType")
                    and name.isidentifier()):
                return super().find_class(module, name)
        raise ValueError(
            f"pickle payload references {module}.{name}; refused "
            "(set -rpc_pickle_unrestricted for trusted peers)")


_SERIALIZERS: dict[str, Serializer] = {}


def register_serializer(s: Serializer) -> None:
    _SERIALIZERS[s.name] = s


def get_serializer(name: str):
    if isinstance(name, Serializer):
        return name
    s = _SERIALIZERS.get(name)
    if s is None:
        raise KeyError(f"unknown serializer {name!r}")
    return s


class TensorFrameSerializer(Serializer):
    """Mixed-payload binary frames (ISSUE 13): inline scalars/strings
    plus dtype/shape-tagged tensors decoded as ZERO-COPY numpy views
    over the transport's IOBuf-backed memoryview — see
    brpc_tpu/rpc/tensorframe.py for the layout and the bounded-decode
    discipline.  Deliberately does NOT touch tensor_host_encodes/
    decodes: those counters belong to the host-materializing tensor
    serializer, and the loopback bench pins their zero growth on this
    path."""

    name = "tensorframe"

    def encode(self, obj):
        from brpc_tpu.rpc.tensorframe import encode_frame
        return encode_frame(obj), b""

    def decode(self, body, tensor_header):
        from brpc_tpu.rpc.tensorframe import decode_frame
        return decode_frame(body)


class CompactSerializer(Serializer):
    """Self-describing compact binary (the mcpack2pb slot — see
    brpc_tpu/rpc/compact.py)."""

    name = "compact"

    def encode(self, obj):
        from brpc_tpu.rpc.compact import dumps
        return dumps(obj), b""

    def decode(self, body, tensor_header):
        from brpc_tpu.rpc.compact import loads
        return loads(as_bytes(body))


for _s in (RawSerializer(), JsonSerializer(), PbSerializer(),
           TensorSerializer(), PickleSerializer(), CompactSerializer(),
           TensorFrameSerializer()):
    register_serializer(_s)


# ---- compression ----

def snappy_compress(data) -> bytes:
    """Native snappy block format (src/cc/butil/snappy.cc; the reference's
    snappy compression policy, global.cpp:393-403)."""
    import ctypes

    from brpc_tpu._core import core
    data = bytes(data)
    if len(data) > 0xFFFFFFFF:
        raise ValueError("snappy length header is 32-bit; chunk upstream")
    cap = core.brpc_snappy_max_compressed_length(len(data))
    buf = ctypes.create_string_buffer(cap)
    n = core.brpc_snappy_compress(data, len(data), buf)
    # string_at copies exactly n bytes; buf.raw[:n] would materialize the
    # full worst-case buffer a second time before slicing
    return ctypes.string_at(buf, n)


def snappy_decompress(data) -> bytes:
    import ctypes

    from brpc_tpu._core import core
    data = bytes(data)
    ulen = core.brpc_snappy_uncompressed_length(data, len(data))
    if ulen < 0:
        raise ValueError("malformed snappy header")
    # Reject length amplification BEFORE allocating: the densest legal
    # element (3-byte copy-2) emits 64 bytes, so output can never exceed
    # ~22x input — a tiny wire message claiming gigabytes is hostile, not
    # compressed (the decode would fail anyway, but only after the
    # allocation it was crafted to trigger).
    if ulen > len(data) * 22 + 64:
        raise ValueError("implausible snappy uncompressed length")
    buf = ctypes.create_string_buffer(max(int(ulen), 1))
    if core.brpc_snappy_decompress(data, len(data), buf, ulen) != 0:
        raise ValueError("malformed snappy body")
    return buf.raw[:ulen]


def compress(data: bytes, ctype: int) -> bytes:
    if ctype == M.COMPRESS_NONE or not data:
        return data
    if ctype == M.COMPRESS_GZIP:
        return _gzip.compress(data, compresslevel=1)
    if ctype == M.COMPRESS_ZLIB:
        return _zlib.compress(data, 1)
    if ctype == M.COMPRESS_SNAPPY:
        return snappy_compress(data)
    if ctype == M.COMPRESS_ZSTD:
        if _zstd is None:
            raise ValueError("zstd not available in this environment")
        return _zstd.ZstdCompressor(level=1).compress(data)
    raise ValueError(f"unknown compress type {ctype}")


def decompress(data: bytes, ctype: int) -> bytes:
    if ctype == M.COMPRESS_NONE or not data:
        return data
    if ctype == M.COMPRESS_GZIP:
        return _gzip.decompress(data)
    if ctype == M.COMPRESS_ZLIB:
        return _zlib.decompress(data)
    if ctype == M.COMPRESS_SNAPPY:
        # Mixed-version tolerance: builds before the native codec shipped
        # zstd frames under wire value 3.  A zstd frame can never be valid
        # snappy here (its magic 0x28B52FFD parses as an implausible
        # varint), so sniffing the magic is unambiguous.
        if bytes(data[:4]) == b"\x28\xb5\x2f\xfd" and _zstd is not None:
            return _zstd.ZstdDecompressor().decompress(bytes(data))
        return snappy_decompress(data)
    if ctype == M.COMPRESS_ZSTD:
        if _zstd is None:
            raise ValueError("zstd not available in this environment")
        return _zstd.ZstdDecompressor().decompress(data)
    raise ValueError(f"unknown compress type {ctype}")
