"""Server — service registry + dispatch (reference server.{h,cpp}; §2.6).

Request path mirrors §3.3: native core parses a frame and hands it to an
executor thread → verify auth → find method in the method map → concurrency
limiter OnRequested → decompress/deserialize → user method → serialize,
compress, write response → MethodStatus::OnResponded feeds per-method
LatencyRecorders (the /status page data).  HTTP messages on the same port go
to the builtin console router (SURVEY.md §2.7).
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from brpc_tpu import errors, flags as _flags, rpcz
from brpc_tpu.rpc import rpc_dump as _rpc_dump  # registers rpc_dump_* flags
from brpc_tpu.bvar import Adder, LatencyRecorder, PassiveStatus
from brpc_tpu.rpc import meta as M
from brpc_tpu.rpc.controller import Controller
from brpc_tpu.rpc.serialization import (PbSerializer, as_bytes, compress,
                                        decompress, get_serializer,
                                        pb_message_pool)
from brpc_tpu.rpc.service import MethodSpec, Service, method
from brpc_tpu.rpc.transport import (MSG_H2, MSG_HTTP, MSG_MEMCACHE,
                                    MSG_MONGO, MSG_REDIS, MSG_THRIFT,
                                    MSG_TRPC, Transport)

# responses whose socket write was rejected (EOVERCROWDED backlog or a
# dead socket) — the client can only learn via its own deadline, so these
# are the server-side visibility: the Adder counts Python-path drops, the
# PassiveStatus mirrors the native fast path's C++ counter onto /vars
_dropped_responses = Adder("rpc_server_dropped_responses")


class _StreamBody:
    """Server-streaming response body: iterates the handler's generator,
    encoding one item per __next__ (bounded by the service's tag pool),
    and guarantees the cleanup callback runs EXACTLY once however the
    stream ends — exhaustion, mid-stream error, or close() before the
    first item (where a plain generator's finally would never run)."""

    _END = object()

    def __init__(self, gen, serializer, pool, cleanup):
        self._gen = gen
        self._ser = serializer
        self._pool = pool
        self._cleanup = cleanup
        self._done = False

    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        try:
            if self._pool is not None:
                item = self._pool.submit(next, self._gen, self._END).result()
            else:
                item = next(self._gen, self._END)
        except BaseException:
            self._settle(errors.EINTERNAL)
            raise
        if item is self._END:
            self._settle(0)
            raise StopIteration
        try:
            body, _ = self._ser.encode(item)
        except BaseException:
            self._settle(errors.EINTERNAL)
            raise
        return body

    def close(self) -> None:
        if self._done:
            return
        try:
            self._gen.close()
        except Exception:
            pass
        self._settle(errors.ECANCELED)

    def _settle(self, code: int) -> None:
        if not self._done:
            self._done = True
            self._cleanup(code)


def _interceptor_code(verdict):
    """Maps an interceptor verdict to an error code, or None to admit.
    ONE implementation for every dispatch path (native, RESTful, gRPC):
    bool is an int subtype and error code 0 reads as success on the
    client, so both `False` and a C-style 0 must mean EREJECT — not a
    silent empty success (interceptor.h:26)."""
    if verdict is None or verdict is True:
        return None
    if isinstance(verdict, int) and not isinstance(verdict, bool) \
            and verdict != 0:
        return verdict
    return errors.EREJECT
_native_dropped = PassiveStatus(
    lambda: __import__("brpc_tpu._core", fromlist=["core"])
    .core.brpc_rpc_dropped_responses()).expose(
        "rpc_native_dropped_responses")


@dataclass
class ServerOptions:
    num_threads: int = 0                   # 0 = native executor default
    max_concurrency: int | str = 0         # 0=unlimited, int, or "auto"
    method_max_concurrency: int | str = 0
    auth: Optional[Any] = None             # Authenticator (verify side)
    interceptor: Optional[Any] = None      # pre-dispatch hook
    internal_port: int = -1                # separate console port (optional)
    has_builtin_services: bool = True
    server_info_name: str = "tpu-rpc"
    graceful_quit_timeout_s: float = 5.0
    # Serve the redis protocol on the same port (reference
    # ServerOptions.redis_service, redis.h:192): a RedisService whose
    # command handlers answer RESP traffic detected by the native parser.
    redis_service: Optional[Any] = None
    # Serve the memcache binary protocol on the same port (the reference
    # is client-only for memcache; server side mirrors redis_service so
    # loopback tests and demos work): a MemcacheService.
    memcache_service: Optional[Any] = None
    # Serve framed-binary thrift on the same port (reference
    # thrift_service.h adaptor): a ThriftService with method handlers.
    thrift_service: Optional[Any] = None
    # Serve the mongo wire protocol (reference mongo_service_adaptor.h):
    # an object with handle_bytes(raw) -> bytes.
    mongo_service: Optional[Any] = None
    # Catch-all service for unmatched (service, method) — the generic
    # proxy hook (reference baidu_master_service.{h,cpp}).  An object with
    # process(cntl, request_bytes) -> bytes; the target names are on
    # cntl.request_meta.service/.method.
    master_service: Optional[Any] = None
    # Per-request pooled session data (reference simple_data_pool +
    # data_factory.h): a DataFactory, or a zero-arg callable; each request
    # sees the pooled object as cntl.session_data.
    session_data_factory: Optional[Any] = None
    # pooled pb request messages (reference RpcPBMessageFactory arena
    # pooling, rpc_pb_message_factory.{h,cpp}).  Opt-in: the framework
    # owns the request message and reuses it after done — handlers that
    # stash the message past completion must copy it first.
    pb_message_pooling: bool = False
    # Advertise this server as ICI-reachable on the given jax device: tensor
    # payloads from in-process channels then ride the BlockPool/IciEndpoint
    # rail instead of the socket (the use_rdma switch — channel.h:109,
    # rdma_endpoint.h:82; see ici/rail.py).
    ici_device: Optional[Any] = None
    # register the _dcn service (topology handshake + remote device-service
    # bridge, ici/dcn.py) at start — the DCN half of SURVEY §5.8
    enable_dcn: bool = False
    # Run handlers in a WIDE dedicated thread pool instead of the
    # fixed-width native executor workers (the reference's
    # FLAGS_usercode_in_pthread + usercode_backup_pool,
    # details/usercode_backup_pool.cpp): handlers that BLOCK (nested
    # RPCs, IO, long sleeps) stop competing for the executor's cores+1
    # workers, so blocking user code cannot starve dispatch of other
    # requests.  Costs a thread hop per request — off by default,
    # exactly like the reference flag.  NOTE: unlike the reference's
    # grow-on-demand backup pool this pool is FIXED-CAP
    # (usercode_pool_workers, default 64) — beyond that many
    # simultaneously blocked handlers, requests queue behind them.
    usercode_in_pthread: bool = False
    # pool width when usercode_in_pthread is on (0 = 64)
    usercode_pool_workers: int = 0
    # Native admission control for the GIL-serialized Python lane
    # (reference ELIMIT fail-fast semantics, expressed as a latency
    # budget): when > 0, a request whose estimated queue wait (pending x
    # EMA upcall time, tracked in C++) exceeds this many milliseconds is
    # answered ELIMIT natively — it never reaches Python.  0 = off, the
    # reference's default.  Process-wide (the native lane is shared).
    usercode_latency_budget_ms: float = 0.0
    # Single-threaded event-loop mode: run handlers INLINE on the native
    # dispatcher thread (no executor hop, no cross-thread GIL convoy —
    # the lowest-variance path on core-starved hosts).  STRICTLY for
    # handlers that never block: a blocking handler stalls every socket
    # on that dispatcher, and a nested RPC through it can deadlock.
    # Process-wide.  Mutually exclusive in spirit with
    # usercode_in_pthread (which exists FOR blocking handlers).
    usercode_inline: bool = False
    # In-socket TLS for the main port (reference ServerSSLOptions /
    # socket.h SSL integration): an ssl.SSLContext with a loaded cert
    # chain; every accepted connection is TLS-wrapped before its first
    # byte parses, and every protocol on the port rides it.  NOTE: do
    # not combine with usercode_latency_budget_ms (its native-packed
    # ELIMIT shed would bypass the TLS engine).
    tls_context: Optional[Any] = None
    # NATIVE h2/gRPC data plane (src/cc/net/h2.cc + rpc/h2_native.py,
    # mirroring the reference's native http2_rpc_protocol.cpp): h2
    # framing, HPACK, flow control and gRPC framing run in C++; Python
    # is upcalled once per message.  Off → the pure-Python plane
    # (rpc/h2.py GrpcServerConnection) serves h2 on the port instead.
    # Forced off under in-socket TLS: the TLS engine re-injects
    # plaintext through the generic parser path on the LISTENER's
    # options, and the native session would bypass the record layer.
    h2_native: bool = True


class MethodStatus:
    """Per-method concurrency + latency tracking
    (reference details/method_status.{h,cpp}).

    The per-request path is native end to end (VERDICT r2 task 5):
    concurrency is a native EXACT atomic (admission control needs a
    linearizable count — a combiner's relaxed cell-walk can transiently
    undercount and over-admit) and latency rides the native combiner
    LatencyRecorder backend — no Python-level lock is taken per
    request."""

    def __init__(self, full_name: str, limiter=None):
        from brpc_tpu._core import core
        safe = full_name.replace("/", "_").replace(".", "_")
        self.full_name = full_name
        self.latency_rec = LatencyRecorder(f"rpc_server_{safe}")
        self.nerror = Adder(f"rpc_server_{safe}_error")
        self._conc_h = core.brpc_atomic_new()
        self._conc_incr = core.brpc_atomic_incr
        self._conc_get = core.brpc_atomic_get
        self._conc_free = core.brpc_atomic_free  # cached for __del__
        self.limiter = limiter
        PassiveStatus(lambda: self.concurrency).expose(
            f"rpc_server_{safe}_concurrency")

    def on_requested(self) -> bool:
        c = self._conc_incr(self._conc_h, 1)
        if self.limiter is not None and not self.limiter.on_requested(c):
            self._conc_incr(self._conc_h, -1)
            return False
        return True

    def on_responded(self, error_code: int, latency_us: int) -> None:
        # self-heal at zero (the old locked max(0, c-1)): an unmatched
        # on_responded must not drive the gauge permanently negative and
        # disable the limiter
        if self._conc_incr(self._conc_h, -1) < 0:
            self._conc_incr(self._conc_h, 1)
        if error_code == 0:
            self.latency_rec.add(latency_us)
        else:
            self.nerror.add(1)
        if self.limiter is not None:
            self.limiter.on_responded(error_code, latency_us)

    @property
    def concurrency(self) -> int:
        return max(0, self._conc_get(self._conc_h))

    def __del__(self):
        h = getattr(self, "_conc_h", None)
        if h:
            try:
                self._conc_free(h)
            except Exception:
                pass
            self._conc_h = None


class Server:
    def __init__(self, options: ServerOptions | None = None, **kw):
        self.options = options or ServerOptions(**kw)
        self._services: dict[str, Service] = {}
        self._methods: dict[tuple[str, str], MethodSpec] = {}
        self._method_status: dict[tuple[str, str], MethodStatus] = {}
        self._listen_sid: Optional[int] = None
        self._port: Optional[int] = None
        self._started = False
        self._stopping = False
        self._inflight = 0
        self._inflight_mu = threading.Lock()
        self._inflight_zero = threading.Event()
        self._inflight_zero.set()
        self._connections: set[int] = set()
        self._conn_mu = threading.Lock()
        self._start_time = time.time()
        self._limiter = None
        # http console router installed at start
        self._http_router = None
        # user HTTP handlers served alongside the builtin console
        self._http_handlers: dict[str, Any] = {}
        # pooled per-request session data (simple_data_pool analog)
        self._session_pool = None
        if self.options.session_data_factory is not None:
            from brpc_tpu.rpc.data_pool import SimpleDataPool
            self._session_pool = SimpleDataPool(
                self.options.session_data_factory)
        if self.options.master_service is not None:
            self._method_status[("*", "*")] = \
                MethodStatus("master_service/process")
        # h2/gRPC connections on the shared port (auto-detected by the
        # native parser via the client preface), sid -> GrpcServerConnection
        self._h2_conns: dict[int, Any] = {}
        # bthread-tag analog: isolated per-tag worker pools + service->tag;
        # sizes recorded so start() can (re)create pools after join()
        self._tag_pools: dict[str, Any] = {}
        self._tag_sizes: dict[str, int] = {}
        self._service_tags: dict[str, str] = {}

    def add_http_handler(self, path: str, fn) -> "Server":
        """Register a custom HTTP handler on the console port; fn(req) may
        return str/bytes, (body, content_type), a full HTTP/1.1 response, or
        a ProgressiveResponse for chunked push."""
        self._http_handlers[path] = fn
        return self

    # ---- registry (Server::AddService, server.h:376) ----

    def add_service(self, service: Service,
                    tag: str | None = None,
                    tag_workers: int = 4) -> "Server":
        """Register a service; an optional ``tag`` runs its handlers on an
        isolated worker pool so one service's load cannot starve another
        (the bthread tag of the reference, task_control.h:90-147 /
        example/bthread_tag_echo_c++).  Untagged services run inline on
        the native dispatch threads."""
        if self._started:
            raise RuntimeError("cannot add services after start")
        name = service.service_name()
        if name in self._services:
            raise ValueError(f"service {name!r} already added")
        if tag is not None:
            if tag == "":
                # "" is the usercode_in_pthread pool's reserved key; a
                # user tag colliding with it would silently replace the
                # wide pool with this tag's width
                raise ValueError('tag "" is reserved (usercode pool); '
                                 'pick a non-empty tag name')
            # validate BEFORE mutating any registry state
            prev = self._tag_sizes.get(tag)
            if prev is not None and prev != tag_workers:
                raise ValueError(
                    f"tag {tag!r} already sized at {prev} workers; "
                    f"conflicting tag_workers={tag_workers}")
        self._services[name] = service
        if tag is not None:
            self._tag_sizes[tag] = tag_workers
            self._service_tags[name] = tag
        from brpc_tpu.policy.concurrency_limiter import create_limiter
        for mname, spec in service.rpc_methods().items():
            key = (name, mname)
            self._methods[key] = spec
            limiter = None
            limit = spec.max_concurrency \
                if spec.max_concurrency is not None \
                else self.options.method_max_concurrency
            if limit:
                limiter = create_limiter(limit)
            self._method_status[key] = MethodStatus(f"{name}/{mname}", limiter)
        return self

    @property
    def services(self) -> dict[str, Service]:
        return dict(self._services)

    @property
    def method_statuses(self) -> dict[tuple[str, str], MethodStatus]:
        return dict(self._method_status)

    # ---- lifecycle (Start/Stop/Join, server.cpp:788,1259,1278) ----

    def start(self, addr: str = "0.0.0.0", port: int = 0) -> "Server":
        if self._started:
            raise RuntimeError("already started")
        self._stopping = False   # support stop()/join()/start() again
        if self.options.max_concurrency:
            from brpc_tpu.policy.concurrency_limiter import create_limiter
            self._limiter = create_limiter(self.options.max_concurrency)
        if self.options.has_builtin_services:
            from brpc_tpu.builtin.router import HttpRouter
            self._http_router = HttpRouter(self)
            # gRPC health protocol (reference grpc_health_check /
            # builtin grpc health): stock grpc health clients call
            # /grpc.health.v1.Health/Check and expect
            # HealthCheckResponse{status: SERVING=1} == pb bytes 08 01
            if "grpc.health.v1.Health" not in self._services:
                outer = self

                class _GrpcHealth(Service):
                    NAME = "grpc.health.v1.Health"

                    @method(request="raw", response="raw")
                    def Check(self, cntl, req):
                        # HealthCheckRequest.service is pb field 1
                        # (length-delimited): empty = whole server
                        svc = ""
                        if len(req) >= 2 and req[0] == 0x0A:
                            n = req[1]
                            svc = req[2:2 + n].decode("utf-8", "replace")
                        if svc and svc not in outer._services:
                            return b"\x08\x03"  # SERVICE_UNKNOWN
                        return b"\x08\x01" if outer.running \
                            else b"\x08\x02"  # NOT_SERVING

                self.add_service(_GrpcHealth())
        from brpc_tpu.bvar.default_variables import expose_default_variables
        expose_default_variables()  # process cpu/rss/fds on /vars (§2.7)
        from brpc_tpu.butil.flight import expose_flight_variables
        expose_flight_variables()   # flight recorder + syscall attribution
        # always-on stage-tagged sampling profiler (ISSUE 6): the
        # /hotspots ring starts with the first server; flag-gated
        # (hotspot_sampler_enabled), live-flippable on /flags
        from brpc_tpu.builtin.sampler import HotspotSampler
        HotspotSampler.ensure_started()
        # (re)create tagged worker pools — join() shuts them down, and a
        # Server may be started again afterwards
        from concurrent.futures import ThreadPoolExecutor
        for tag, workers in self._tag_sizes.items():
            if tag not in self._tag_pools:
                self._tag_pools[tag] = ThreadPoolExecutor(
                    max_workers=workers,
                    thread_name_prefix=f"svc-tag-{tag}")
        if self.options.usercode_in_pthread:
            # the usercode pool IS a tag pool under the reserved ""
            # tag: creation here, recreation after join(), shutdown and
            # inflight accounting all ride the one mechanism
            if "" not in self._tag_pools:
                self._tag_pools[""] = ThreadPoolExecutor(
                    max_workers=self.options.usercode_pool_workers or 64,
                    thread_name_prefix="usercode")
        if self.options.usercode_inline and (
                self.options.usercode_in_pthread or self._tag_sizes):
            # pooled handlers under inline dispatch would defeat BOTH
            # features: the inline upcall measures only the pool-submit
            # cost (admission control silently dead while the pool queue
            # grows) and the pool hop reintroduces the cross-thread
            # convoy inline mode exists to remove
            raise ValueError(
                "usercode_inline is for handlers that run inline and "
                "never block; it cannot be combined with "
                "usercode_in_pthread or per-service tag pools")
        if self.options.usercode_latency_budget_ms > 0 or \
                self.options.usercode_inline:
            from brpc_tpu._core import core as _core
            if self.options.usercode_latency_budget_ms > 0:
                _core.brpc_set_usercode_budget_us(
                    int(self.options.usercode_latency_budget_ms * 1000))
            if self.options.usercode_inline:
                _core.brpc_set_usercode_inline(1)
            _usercode_policy_owners.add(id(self))
        if self.options.enable_dcn:
            # cross-process device RPC: topology handshake + remote
            # device-service bridge (ici/dcn.py; the RdmaEndpoint
            # TCP-assisted-handshake slot, rdma_endpoint.h:112-115).
            # Added BEFORE the native-registration loop below so DCN
            # methods ride the same path as every other service.
            from brpc_tpu.ici.dcn import DCN_SERVICE, DcnService
            if DCN_SERVICE not in self._services:
                self.add_service(DcnService())
        t = Transport.instance()
        use_native_h2 = (self.options.h2_native
                         and self.options.tls_context is None)
        if use_native_h2:
            from brpc_tpu.rpc.h2_native import NativeH2Bridge
            self._h2_bridge = NativeH2Bridge(self)
            self._listen_sid, self._port = t.listen_rpc_h2(
                addr, port, self._on_message, self._h2_bridge,
                on_failed=self._on_conn_failed,
                on_request=self._on_fast_request)
        else:
            self._listen_sid, self._port = t.listen_rpc(
                addr, port, self._on_message, self._on_conn_failed,
                on_request=self._on_fast_request)
        if self.options.tls_context is not None:
            if self.options.usercode_latency_budget_ms > 0:
                # the native ELIMIT shed packs and writes PLAINTEXT
                # directly, bypassing the TLS engine: under overload the
                # error response would leak in cleartext and kill the
                # session — refuse the combination up front
                raise ValueError(
                    "tls_context cannot be combined with "
                    "usercode_latency_budget_ms (the native shed path "
                    "bypasses the TLS engine)")
            t.enable_tls_listener(self._listen_sid, self.options.tls_context)
        # native method map (FlatMap behind DoublyBufferedData, net/rpc.h):
        # requests to these methods are meta-parsed and method-matched in
        # C++ and arrive pre-parsed; everything else (auth/trace/stream
        # metas, unknown methods, master-service catch-all) still comes
        # through _on_message with full Python decode
        for key in self._methods:
            _native_method_register(key)
        self._methods_registered = True
        if self.options.ici_device is not None:
            from brpc_tpu.ici import rail
            rail.advertise(self._port, self.options.ici_device)
        self._started = True
        self._start_time = time.time()
        _register_server(self)
        return self

    @property
    def port(self) -> Optional[int]:
        return self._port

    @property
    def running(self) -> bool:
        return self._started and not self._stopping

    def stop(self) -> None:
        """Stop accepting; in-flight requests drain in join()."""
        if not self._started or self._stopping:
            return
        self._stopping = True
        if self.options.ici_device is not None and self._port is not None:
            from brpc_tpu.ici import rail
            rail.unadvertise(self._port)
        if self._listen_sid is not None:
            Transport.instance().close(self._listen_sid)

    def join(self) -> None:
        if not self._started:
            return  # idempotent: a second join() must not double-unregister
        self._stopping = True  # decrements only signal the event when stopping
        with self._inflight_mu:
            if self._inflight == 0:
                self._inflight_zero.set()
            else:
                self._inflight_zero.clear()
        self._inflight_zero.wait(self.options.graceful_quit_timeout_s)
        with self._conn_mu:
            conns = list(self._connections)
        t = Transport.instance()
        for sid in conns:
            t.close(sid)
        for pool in self._tag_pools.values():
            pool.shutdown(wait=False)
        self._tag_pools.clear()   # start() recreates from _tag_sizes
        if getattr(self, "_methods_registered", False):
            self._methods_registered = False
            for key in self._methods:
                _native_method_unregister(key)
        if self.options.usercode_latency_budget_ms > 0 or \
                self.options.usercode_inline:
            # budget/inline are process-wide native state: clear only
            # when the LAST owning server leaves, so stopping one server
            # can't strip admission control from another still running
            from brpc_tpu._core import core as _core
            _usercode_policy_owners.discard(id(self))
            if not _usercode_policy_owners:
                _core.brpc_set_usercode_budget_us(0)
                _core.brpc_set_usercode_inline(0)
        _unregister_server(self)
        self._started = False

    def run_until_interrupt(self) -> None:  # RunUntilAskedToQuit analog
        try:
            while self.running:
                time.sleep(0.5)
        except KeyboardInterrupt:
            pass
        self.stop()
        self.join()

    # ---- stats for builtins ----

    @property
    def uptime_s(self) -> float:
        return time.time() - self._start_time

    @property
    def connection_count(self) -> int:
        with self._conn_mu:
            return len(self._connections)

    def connections(self) -> list[int]:
        with self._conn_mu:
            return list(self._connections)

    # ---- dispatch ----

    def _on_conn_failed(self, sid: int, err: int) -> None:
        with self._conn_mu:
            self._connections.discard(sid)
        conn = self._h2_conns.pop(sid, None)
        if conn is not None:
            # unblock bidi handlers parked on this connection's request
            # queues, or they leak their inflight slots forever
            conn.abort_bidi()

    def _track_conn(self, sid: int) -> None:
        if sid in self._connections:  # GIL-safe read; hot path skips the lock
            return
        with self._conn_mu:
            self._connections.add(sid)

    def _on_message(self, sid: int, kind: int, meta_bytes: bytes, body) -> None:
        self._track_conn(sid)
        if kind == MSG_HTTP:
            if self._http_router is not None:
                self._http_router.handle(sid, body.to_bytes())
            else:
                Transport.instance().write_raw(
                    sid, b"HTTP/1.1 404 Not Found\r\nContent-Length: 0\r\n\r\n")
            return
        if kind == MSG_H2:
            conn = self._h2_conns.get(sid)
            if conn is None:
                from brpc_tpu.rpc.h2 import GrpcServerConnection, \
                    feed_frames
                self._h2_feed = feed_frames   # hot path: no per-msg import
                conn = self._h2_conns[sid] = GrpcServerConnection(sid, self)
            self._h2_feed(conn, meta_bytes, body.to_bytes())
            return
        if kind == MSG_REDIS:
            svc = self.options.redis_service
            if svc is None:
                Transport.instance().write_raw(
                    sid, b"-ERR this server has no redis service\r\n")
            else:
                Transport.instance().write_raw(
                    sid, svc.handle_bytes(body.to_bytes()))
            return
        if kind == MSG_MEMCACHE:
            svc = self.options.memcache_service
            if svc is None:
                # binary "unknown command" so clients fail fast
                from brpc_tpu.rpc.memcache import (MAGIC_RES,
                                                   ST_UNKNOWN_COMMAND,
                                                   pack_packet)
                Transport.instance().write_raw(
                    sid, pack_packet(MAGIC_RES, 0,
                                     status=ST_UNKNOWN_COMMAND))
            else:
                Transport.instance().write_raw(
                    sid, svc.handle_bytes(body.to_bytes()))
            return
        if kind == MSG_THRIFT:
            svc = self.options.thrift_service
            if svc is None:
                from brpc_tpu.rpc.thrift import (decode_message,
                                                 encode_exception)
                try:
                    req = decode_message(body.to_bytes())
                    name, seqid = req.name, req.seqid
                except ValueError:
                    name, seqid = "unknown", 0
                Transport.instance().write_raw(
                    sid, encode_exception(name, seqid,
                                          "this server has no thrift "
                                          "service", 1))
            else:
                out = svc.handle_bytes(body.to_bytes())
                if out:
                    Transport.instance().write_raw(sid, out)
            return
        if kind == MSG_MONGO:
            svc = self.options.mongo_service
            if svc is None:
                # no silent drop: close so mongo drivers fail fast instead
                # of blocking on recv forever
                Transport.instance().close(sid)
            else:
                out = svc.handle_bytes(body.to_bytes())
                if out:
                    Transport.instance().write_raw(sid, out)
            return
        try:
            meta = M.RpcMeta.decode(meta_bytes)
        except ValueError:
            return
        if meta.msg_type == M.MSG_REQUEST:
            self._route_request(sid, meta, body, meta_bytes)
        elif meta.msg_type in (M.MSG_STREAM_DATA, M.MSG_STREAM_FEEDBACK,
                               M.MSG_STREAM_CLOSE):
            from brpc_tpu.rpc.stream import StreamRegistry
            StreamRegistry.instance().on_frame(sid, meta, body)

    def _inflight_inc(self) -> None:
        # Hot path: a bare counter under the lock.  The zero-event is only
        # observed by join(), so Event.set()/clear() churn (measured
        # ~6us/request — notify_all allocates and wakes) happens ONLY while
        # stopping, not per request.
        with self._inflight_mu:
            self._inflight += 1

    def _inflight_dec(self) -> None:
        with self._inflight_mu:
            self._inflight -= 1
            if self._inflight == 0 and self._stopping:
                self._inflight_zero.set()

    def _on_fast_request(self, sid: int, cid: int, attempt: int,
                         service: str, method_name: str, compress: int,
                         timeout_ms: int, content_type: str,
                         attachment_size: int, body: bytes) -> None:
        """Natively pre-parsed request (net/rpc.h fast path via _fastrpc):
        the meta TLV walk, method lookup and frame cut all happened in C++;
        only the handler body and response serialization run in Python."""
        self._track_conn(sid)
        meta = M.RpcMeta(
            msg_type=M.MSG_REQUEST,
            correlation_id=cid,
            attempt=attempt,
            service=service,
            method=method_name,
            compress_type=compress,
            timeout_ms=timeout_ms,
            content_type=content_type,
            attachment_size=attachment_size,
        )
        self._route_request(sid, meta, body, None)

    def _route_request(self, sid: int, meta: M.RpcMeta, body,
                       meta_bytes: bytes | None) -> None:
        # sampled traffic capture for rpc_replay (rpc_dump.h:69, §5.5);
        # the body copy (and the fast path's meta re-encode) happen only
        # when dumping is on
        if _flags.get_flag("rpc_dump"):
            from brpc_tpu.rpc.rpc_dump import RpcDumper
            from brpc_tpu.rpc.serialization import as_bytes
            RpcDumper.instance().sample(
                meta_bytes or meta.encode(),
                as_bytes(body) if isinstance(body, (bytes, memoryview))
                else body.to_bytes())
        tag = self._service_tags.get(meta.service)
        pool = self._tag_pools.get(tag) if tag is not None else None
        if pool is None:
            # usercode_in_pthread (usercode_backup_pool.cpp): BLOCKING
            # handlers hop to the wide "" tag pool so they never park
            # the fixed-width executor workers dispatching everyone else
            pool = self._tag_pools.get("")
        if pool is not None:
            if self._stopping:
                # the pre_accepted contract covers requests QUEUED
                # before stop(); a request ARRIVING after stop() gets
                # ELOGOFF here, same as the non-pool path's gate
                self._respond_error(sid, meta, errors.ELOGOFF)
                return
            # isolated worker pool for this service (bthread tag);
            # count the QUEUED request so graceful join() waits for it
            self._inflight_inc()
            pool.submit(self._process_tagged, sid, meta, body)
        else:
            self._process_request(sid, meta, body)

    def _process_tagged(self, sid: int, meta: M.RpcMeta, body) -> None:
        try:
            # pre_accepted: this request entered the queue before any
            # stop(); graceful join() is waiting for it — serve it
            self._process_request(sid, meta, body, pre_accepted=True)
        finally:
            self._inflight_dec()

    def _respond_error(self, sid: int, meta: M.RpcMeta, code: int,
                       text: str = "") -> None:
        # error responses carry only cid/attempt/error TLVs: pack natively
        Transport.send_response(sid, meta.correlation_id, meta.attempt,
                                code, text or errors.describe(code), "", b"")

    def _process_request(self, sid: int, meta: M.RpcMeta, body,
                         pre_accepted: bool = False) -> None:
        """ProcessRpcRequest analog (baidu_rpc_protocol.cpp:398)."""
        start = time.monotonic()
        if self._stopping and not pre_accepted:
            self._respond_error(sid, meta, errors.ELOGOFF)
            return
        # auth (§2.5 Auth: first-message piggyback — we verify every frame)
        if self.options.auth is not None:
            if not self.options.auth.verify_credential(meta.auth):
                self._respond_error(sid, meta, errors.ERPCAUTH)
                return
        # interceptor (interceptor.h:26)
        if self.options.interceptor is not None:
            code = _interceptor_code(self.options.interceptor(meta))
            if code is not None:
                self._respond_error(sid, meta, code)
                return
        key = (meta.service, meta.method)
        spec = self._methods.get(key)
        if spec is None:
            master = self.options.master_service
            if master is not None:
                # catch-all dispatch (baidu_master_service: generic method
                # for proxies, baidu_rpc_protocol.cpp:521-560); raw bytes
                # in/out, target names readable off cntl.request_meta
                key = ("*", "*")
                spec = MethodSpec(
                    name="process",
                    fn=lambda cntl, req: master.process(cntl, req),
                    request_serializer=get_serializer("raw"),
                    response_serializer=get_serializer("raw"))
            elif meta.service not in self._services:
                self._respond_error(sid, meta, errors.ENOSERVICE,
                                    f"unknown service {meta.service!r}")
                return
            else:
                self._respond_error(sid, meta, errors.ENOMETHOD,
                                    f"unknown method {meta.method!r}")
                return
        # server-level then method-level concurrency (§2.6)
        if self._limiter is not None and not self._limiter.on_requested(
                self._total_concurrency() + 1):
            self._respond_error(sid, meta, errors.ELIMIT)
            return
        status = self._method_status[key]
        if not status.on_requested():
            if self._limiter is not None:
                self._limiter.on_responded(errors.ELIMIT, 0)
            self._respond_error(sid, meta, errors.ELIMIT)
            return

        self._inflight_inc()

        span = rpcz.new_span("server", meta.service, meta.method,
                             trace_id=meta.trace_id,
                             parent_span_id=meta.span_id,
                             # a joined trace inherits the root's
                             # head-sampling decision from the wire;
                             # a fresh trace (no id) decides locally
                             sampled=bool(meta.flags
                                          & M.FLAG_TRACE_SAMPLED)
                             if meta.trace_id else None)
        cntl = Controller()
        cntl.is_server_side = True
        cntl.request_meta = meta
        cntl.peer_sid = sid
        cntl.trace_id = span.trace_id
        cntl.span_id = span.span_id
        rail_src = meta.user_fields.get(M.F_SRC_DEV) \
            if meta.user_fields else None
        # ---- decode phase ----
        try:
            if meta.user_fields.get(M.F_TICKET):
                # request payload rode ICI: claim the device arrays from the
                # rail registry (ici/rail.py) — the frame carried only the
                # ticket, no body bytes exist
                from brpc_tpu.ici import rail
                request = rail.claim(meta.user_fields[M.F_TICKET])
                span.request_size = 0
            else:
                # fast-path bodies arrive as IOBuf-backed memoryviews
                # (zero copy, _fastrpc FastBody); the generic path hands
                # an IOBuf.  memoryview slicing keeps it zero-copy.
                raw = body if isinstance(body, (bytes, memoryview)) \
                    else body.to_bytes()
                att = meta.attachment_size
                payload = raw[: len(raw) - att] if att else raw
                # bytes contract for attachments (same boundary rule as
                # the raw serializer): handlers get bytes, not views
                cntl.request_attachment = bytes(raw[len(raw) - att:]) \
                    if att else b""
                if meta.compress_type:
                    payload = decompress(payload, meta.compress_type)
                req_ser = spec.request_serializer
                if (self.options.pb_message_pooling
                        and isinstance(req_ser, PbSerializer)
                        and req_ser.message_class is not None):
                    # pooled request message (RpcPBMessageFactory slot);
                    # returned to the pool after done fires
                    request = pb_message_pool.get(req_ser.message_class)
                    cntl._pooled_request = request  # BEFORE parse: a
                    # parse failure path still returns it to the pool
                    request.ParseFromString(as_bytes(payload))
                else:
                    request = req_ser.decode(payload, meta.tensor_header)
                span.request_size = len(raw)
                # request wire size surfaced to handlers (per-serializer
                # wire-bytes accounting, e.g. psserve_wire_bytes_*)
                cntl.request_body_size = len(raw)
        except Exception as e:
            if isinstance(e, ValueError):
                # malformed payload = bad INPUT, not a server bug: every
                # serializer's malformed-body path raises ValueError (the
                # contract serialization.py documents), and the peer must
                # see a clean EREQUEST instead of EINTERNAL — the
                # tensorframe fuzz surface pins this
                e = errors.RpcError(errors.EREQUEST,
                                    f"cannot decode request: {e}")
            self._complete_request(sid, meta, span, cntl, spec, status,
                                   start, rail_src, None, exc=e)
            return
        # ---- handler phase ----
        # The done closure runs the response path exactly once; a handler
        # that calls cntl.defer() parks the RPC as that closure (data,
        # not a thread) and any thread releases it later — the
        # reference's done Closure (svc->CallMethod(..., done),
        # baidu_rpc_protocol.cpp:398).  It is built LAZILY by defer():
        # the common synchronous path completes inline below without
        # paying a closure + once-guard lock per request.
        cntl._done_factory = lambda: self._make_server_done(
            sid, meta, span, cntl, spec, status, start, rail_src)
        traced = span is not rpcz.NULL_SPAN
        if traced:  # with rpcz off, skip the contextvar pair per request
            rpcz.set_current_span(span)
        if self._session_pool is not None:
            cntl.session_data = self._session_pool.borrow()
        try:
            response = spec.fn(cntl, request)
        except Exception as e:
            if cntl._deferred:
                # defer() transferred response ownership to done(); the
                # raise is a handler bug but completing here would race
                # the legitimate done() (reference contract: after done is
                # handed to CallMethod the framework never responds on
                # handler return — a leaked done hangs, an owned one wins)
                import traceback
                traceback.print_exc()
                return
            self._complete_request(sid, meta, span, cntl, spec, status,
                                   start, rail_src, None, exc=e)
            return
        finally:
            if traced:
                rpcz.set_current_span(None)
            if self._session_pool is not None:
                # deferred handlers must not rely on session_data after
                # returning: the pooled object goes back with the handler
                self._session_pool.give_back(cntl.session_data)
                cntl.session_data = None
        if cntl._deferred:
            return  # the parked done() closure completes the RPC later
        self._complete_request(sid, meta, span, cntl, spec, status,
                               start, rail_src, response)

    def _make_server_done(self, sid, meta, span, cntl, spec, status,
                          start, rail_src):
        """One-shot done(response) closure for DEFERRED completion —
        built only when a handler actually calls cntl.defer()."""
        fired = [False]
        fired_mu = threading.Lock()

        def done(response=None):
            with fired_mu:
                if fired[0]:
                    raise RuntimeError(
                        f"done() called twice for "
                        f"{meta.service}.{meta.method}"
                        f" cid={meta.correlation_id}")
                fired[0] = True
            self._complete_request(sid, meta, span, cntl, spec, status,
                                   start, rail_src, response)

        return done

    def _complete_request(self, sid: int, meta: M.RpcMeta, span, cntl,
                          spec, status, start: float, rail_src,
                          response, exc: Exception | None = None) -> None:
        """Response path + accounting (SendRpcResponse analog,
        baidu_rpc_protocol.cpp:187).  Runs exactly once per accepted
        request — inline for plain handlers, from done() for deferred
        ones."""
        # completion consumes the lazy done factory: a handler that
        # already responded and calls defer() afterwards now fails
        # loudly in defer() instead of minting a fresh once-guard and
        # double-sending
        cntl._done_factory = None
        error_code = 0
        try:
            if exc is not None:
                raise exc
            if cntl.failed():
                error_code = cntl.error_code
                if cntl.response_user_fields:
                    # fields ride FAILED completions too (the reference
                    # packs user fields on error responses): rich meta
                    # instead of the minimal native error pack
                    err = M.RpcMeta(msg_type=M.MSG_RESPONSE,
                                    correlation_id=meta.correlation_id,
                                    attempt=meta.attempt,
                                    error_code=cntl.error_code,
                                    error_text=cntl.error_text or
                                    errors.describe(cntl.error_code))
                    err.user_fields.update(M.normalize_user_fields(
                        cntl.response_user_fields))
                    Transport.instance().write_frame(sid, err.encode(), b"")
                else:
                    self._respond_error(sid, meta, cntl.error_code,
                                        cntl.error_text)
            elif rail_src is not None and self._ship_rail_response(
                    sid, meta, span, cntl, response, rail_src):
                pass  # response rode ICI; control frame already written
            else:
                res_ser = spec.response_serializer
                rbody, theader = res_ser.encode(response)
                if meta.compress_type:
                    rbody = compress(rbody, meta.compress_type)
                if (cntl._stream is None and not cntl.response_attachment
                        and not theader and not meta.compress_type
                        and not span.trace_id
                        and not cntl.response_user_fields):
                    # plain response: cid/attempt/content_type only — pack
                    # the meta and frame natively (PackResponseFrame)
                    span.response_size = len(rbody)
                    rc = Transport.send_response(
                        sid, meta.correlation_id, meta.attempt, 0, "",
                        res_ser.name, rbody)
                    if rc != 0:
                        # the response frame was dropped (overcrowded
                        # write queue or dead socket): nothing can reach
                        # this client, but the accounting must not claim
                        # success (reference SendRpcResponse logs the
                        # Write failure the same way)
                        error_code = errors.EOVERCROWDED if rc == -2 \
                            else errors.EFAILEDSOCKET
                        _dropped_responses.add(1)
                else:
                    resp = M.RpcMeta(msg_type=M.MSG_RESPONSE,
                                     correlation_id=meta.correlation_id,
                                     attempt=meta.attempt,
                                     compress_type=meta.compress_type,
                                     content_type=res_ser.name,
                                     tensor_header=theader,
                                     trace_id=span.trace_id,
                                     span_id=span.span_id)
                    if cntl.response_user_fields:
                        # same contract as the request side — ONE shared
                        # validation (meta.normalize_user_fields)
                        resp.user_fields.update(M.normalize_user_fields(
                            cntl.response_user_fields))
                    if cntl._stream is not None:
                        # tell the client our local stream id + window size
                        # (StreamSettings exchange in the reference)
                        resp.stream_id = cntl._stream.stream_id
                        resp.user_fields[M.F_SBUF] = \
                            str(cntl._stream.max_buf_size)
                        if cntl._stream.device is not None:
                            from brpc_tpu.ici import rail
                            resp.user_fields[M.F_SDEV] = \
                                rail.device_advert(cntl._stream.device)
                    if cntl.response_attachment:
                        resp.attachment_size = len(cntl.response_attachment)
                        rbody = rbody + cntl.response_attachment
                    span.response_size = len(rbody)
                    rc = Transport.instance().write_frame(sid, resp.encode(),
                                                          rbody)
                    if rc != 0:
                        error_code = errors.EOVERCROWDED if rc == -2 \
                            else errors.EFAILEDSOCKET
                        _dropped_responses.add(1)
        except errors.RpcError as e:
            # a typed failure keeps its code on the wire (the decode
            # phase wraps malformed payloads as EREQUEST; EINTERNAL for
            # those would misreport bad input as a server bug)
            error_code = e.code
            self._respond_error(sid, meta, e.code, str(e))
        except Exception as e:
            error_code = errors.EINTERNAL
            self._respond_error(sid, meta, errors.EINTERNAL,
                                f"{type(e).__name__}: {e}")
        finally:
            pooled = getattr(cntl, "_pooled_request", None)
            if pooled is not None:
                # the framework owns the request message; done has fired,
                # so return it (RpcPBMessageFactory Return semantics)
                cntl._pooled_request = None
                pb_message_pool.give_back(pooled)
            latency_us = int((time.monotonic() - start) * 1e6)
            status.on_responded(error_code, latency_us)
            if self._limiter is not None:
                self._limiter.on_responded(error_code, latency_us)
            span.error_code = error_code
            span.end_us = rpcz.now_us()
            rpcz.submit(span)
            self._inflight_dec()

    def _ship_rail_response(self, sid: int, meta: M.RpcMeta, span, cntl,
                            response, rail_src: str) -> bool:
        """Return the response over the ICI rail: stage the handler's device
        arrays, transfer them to the requester's device, and write a
        control-only response frame carrying the claim ticket.  Returns
        False (caller host-serializes) when the response isn't device
        arrays, the transfer fails, or the response needs frame features
        the rail's control-only frame doesn't carry (stream settings,
        attachment bytes, user fields)."""
        from brpc_tpu.ici import rail
        if cntl._stream is not None or cntl.response_attachment \
                or cntl.response_user_fields:
            # user fields would be silently lost on the control-only
            # frame; the host path carries them
            return False
        if not rail.railable(response):
            return False
        try:
            target = rail.device_by_id(int(rail_src))
            ticket = rail.ship(response, target)
        except Exception:
            rail.rail_fallbacks.add(1)
            return False
        resp = M.RpcMeta(msg_type=M.MSG_RESPONSE,
                         correlation_id=meta.correlation_id,
                         attempt=meta.attempt,
                         content_type="tensor",
                         trace_id=span.trace_id,
                         span_id=span.span_id)
        resp.user_fields[M.F_TICKET] = ticket
        span.response_size = 0
        if Transport.instance().write_frame(sid, resp.encode(), b"") != 0:
            # peer gone: the ticket would leak until TTL — free it now
            rail.withdraw(ticket)
        return True

    def _total_concurrency(self) -> int:
        return sum(s.concurrency for s in self._method_status.values())

    # ---- RESTful bridge entry (builtin/router.py) ----

    def invoke_restful(self, service: str, method_name: str, payload):
        """Call a method on behalf of the HTTP JSON bridge, through the SAME
        gates as RPC traffic: auth (refused — HTTP carries no credential),
        interceptor, concurrency limiters, MethodStatus and inflight
        accounting.  Raises RpcError on any refusal."""
        if self._stopping:
            raise errors.RpcError(errors.ELOGOFF)
        if self.options.auth is not None:
            raise errors.RpcError(
                errors.ERPCAUTH, "RESTful access disabled on authed server")
        meta = M.RpcMeta(msg_type=M.MSG_REQUEST, service=service,
                         method=method_name, content_type="json")
        if self.options.interceptor is not None:
            code = _interceptor_code(self.options.interceptor(meta))
            if code is not None:
                raise errors.RpcError(code)
        key = (service, method_name)
        spec = self._methods.get(key)
        if spec is None:
            raise errors.RpcError(
                errors.ENOSERVICE if service not in self._services
                else errors.ENOMETHOD)
        if self._limiter is not None and not self._limiter.on_requested(
                self._total_concurrency() + 1):
            raise errors.RpcError(errors.ELIMIT)
        status = self._method_status[key]
        if not status.on_requested():
            if self._limiter is not None:
                self._limiter.on_responded(errors.ELIMIT, 0)
            raise errors.RpcError(errors.ELIMIT)
        self._inflight_inc()
        start = time.monotonic()
        error_code = 0
        try:
            cntl = Controller()
            cntl.is_server_side = True
            # json2pb bridge (reference json2pb/, restful.cpp): pb-typed
            # methods get the JSON body parsed into their message class,
            # and pb responses render back as JSON-able dicts
            from brpc_tpu.rpc.serialization import PbSerializer
            req_ser = spec.request_serializer
            if isinstance(req_ser, PbSerializer) and \
                    req_ser.message_class is not None and \
                    isinstance(payload, dict):
                from google.protobuf import json_format
                try:
                    payload = json_format.ParseDict(
                        payload, req_ser.message_class())
                except json_format.ParseError as e:
                    # client error (bad field/shape), not a server fault
                    raise errors.RpcError(errors.EREQUEST,
                                          f"json2pb: {e}")
            tag = self._service_tags.get(service)
            pool = self._tag_pools.get(tag) if tag is not None else None
            if pool is not None:
                # RESTful traffic honors the service's isolated pool too
                result = pool.submit(spec.fn, cntl, payload).result()
            else:
                result = spec.fn(cntl, payload)
            if result is not None and hasattr(result, "DESCRIPTOR"):
                from google.protobuf import json_format
                # proto field names, not camelCase: clients must get back
                # the same keys they sent (reference json2pb behavior)
                result = json_format.MessageToDict(
                    result, preserving_proto_field_name=True)
            if cntl.failed():
                error_code = cntl.error_code
                raise errors.RpcError(cntl.error_code, cntl.error_text)
            return result
        except errors.RpcError:
            raise
        except Exception as e:
            error_code = errors.EINTERNAL
            raise errors.RpcError(errors.EINTERNAL,
                                  f"{type(e).__name__}: {e}")
        finally:
            latency_us = int((time.monotonic() - start) * 1e6)
            status.on_responded(error_code, latency_us)
            if self._limiter is not None:
                self._limiter.on_responded(error_code, latency_us)
            self._inflight_dec()

    # ---- gRPC entry (policy/http2_rpc_protocol.cpp server role) ----

    def invoke_grpc(self, service: str, method_name: str, payload: bytes,
                    headers: dict[str, str],
                    peer_sid: Optional[int] = None,
                    payload_iter=None) -> tuple[bytes, int, str]:
        """Dispatch one gRPC request through the SAME gates as native
        traffic.  Returns (response_payload, error_code, error_text); the
        h2 connection maps error_code to a grpc-status trailer.
        payload_iter (BIDI): a live iterator of raw request messages —
        the handler receives a lazily-decoding iterator and may consume
        it while producing responses."""
        if self._stopping:
            return b"", errors.ELOGOFF, "server stopping"
        reg_name = service
        if service not in self._services and "." in service:
            # gRPC paths carry package-qualified names; fall back to the
            # bare service name our registry may have used
            bare = service.rsplit(".", 1)[1]
            if bare in self._services:
                reg_name = bare
        key = (reg_name, method_name)
        spec = self._methods.get(key)
        meta = M.RpcMeta(msg_type=M.MSG_REQUEST, service=key[0],
                         method=method_name, content_type="pb",
                         auth=headers.get("authorization", "").encode())
        if self.options.auth is not None:
            if not self.options.auth.verify_credential(meta.auth):
                return b"", errors.ERPCAUTH, "bad credential"
        if self.options.interceptor is not None:
            code = _interceptor_code(self.options.interceptor(meta))
            if code is not None:
                return b"", code, errors.describe(code)
        if spec is None:
            master = self.options.master_service
            if master is not None:
                # catch-all proxy dispatch, same as native traffic
                # (baidu_master_service, baidu_rpc_protocol.cpp:521-560)
                key = ("*", "*")
                spec = MethodSpec(
                    name="process",
                    fn=lambda cntl, req: master.process(cntl, req),
                    request_serializer=get_serializer("raw"),
                    response_serializer=get_serializer("raw"))
            elif key[0] not in self._services:
                return b"", errors.ENOSERVICE, f"unknown service {service!r}"
            else:
                return b"", errors.ENOMETHOD, f"unknown method {method_name!r}"
        if self._limiter is not None and not self._limiter.on_requested(
                self._total_concurrency() + 1):
            return b"", errors.ELIMIT, "server concurrency limit"
        status = self._method_status[key]
        if not status.on_requested():
            if self._limiter is not None:
                self._limiter.on_responded(errors.ELIMIT, 0)
            return b"", errors.ELIMIT, "method concurrency limit"
        self._inflight_inc()
        span = rpcz.new_span("server", key[0], method_name)
        span.annotate("protocol=grpc")
        start = time.monotonic()
        error_code = 0
        text = ""
        resp = b""
        streaming = False

        def _finish(code: int) -> None:
            # accounting + resource release, exactly once per call.  For
            # unary calls it runs in this function's finally; a STREAMING
            # call defers it to the end of frame transmission so graceful
            # join() waits for in-flight streams and the session object
            # stays borrowed while the generator body still runs.
            latency_us = int((time.monotonic() - start) * 1e6)
            status.on_responded(code, latency_us)
            if self._limiter is not None:
                self._limiter.on_responded(code, latency_us)
            span.error_code = code
            span.end_us = rpcz.now_us()
            rpcz.submit(span)
            self._inflight_dec()

        cntl = None
        try:
            if payload_iter is not None:
                # BIDI: decode lazily as the handler pulls
                req_ser = spec.request_serializer
                request = (req_ser.decode(p, "") for p in payload_iter)
                span.request_size = 0
            elif isinstance(payload, list):
                # CLIENT-STREAMING: one decoded message per request
                # frame; the handler receives the list
                request = [spec.request_serializer.decode(p, "")
                           for p in payload]
                span.request_size = sum(len(p) for p in payload)
            else:
                request = spec.request_serializer.decode(payload, "")
                span.request_size = len(payload)
            cntl = Controller()
            cntl.is_server_side = True
            cntl.request_meta = meta
            cntl.request_headers = dict(headers)   # gRPC metadata surface
            cntl.peer_sid = peer_sid
            rpcz.set_current_span(span)
            if self._session_pool is not None:
                cntl.session_data = self._session_pool.borrow()
            tag = self._service_tags.get(key[0])
            pool = self._tag_pools.get(tag) if tag is not None else None
            result = None
            try:
                if pool is not None:
                    # honor the service's isolated pool for gRPC too: the
                    # calling h2 worker blocks, but handler CONCURRENCY is
                    # bounded by the tag pool like native traffic
                    result = pool.submit(spec.fn, cntl, request).result()
                else:
                    result = spec.fn(cntl, request)
            finally:
                rpcz.set_current_span(None)
                # a streaming result keeps its session until the
                # generator finishes (the body runs per-item, later)
                if self._session_pool is not None and \
                        not hasattr(result, "__next__"):
                    self._session_pool.give_back(cntl.session_data)
                    cntl.session_data = None
            if cntl.failed():
                error_code, text = cntl.error_code, cntl.error_text
                if hasattr(result, "__next__"):
                    # failed AND returned a generator: the streaming
                    # branch below won't run, so release its resources
                    # here (the generator body never executes)
                    try:
                        result.close()
                    except Exception:
                        pass
                    if self._session_pool is not None:
                        self._session_pool.give_back(cntl.session_data)
                        cntl.session_data = None
            elif hasattr(result, "__next__"):
                # SERVER-STREAMING: each item is encoded lazily as the h2
                # layer pulls it into one gRPC frame.  Item production
                # stays bounded by the service's tag pool (one submit per
                # item); cleanup (session give-back + _finish accounting)
                # runs when the stream ends HOWEVER it ends — including
                # close() before the first item (a plain generator's
                # finally never runs if iteration never starts, which
                # leaked the inflight slot when the h2 layer bailed
                # between handler return and transmission).
                streaming = True
                span.annotate("server-streaming")

                def _cleanup(code, cn=cntl):
                    if self._session_pool is not None:
                        self._session_pool.give_back(cn.session_data)
                        cn.session_data = None
                    _finish(code)

                # BIDI handlers legitimately block awaiting the peer's
                # next message; pulling their items through the bounded
                # tag pool would park a pool worker for the call's
                # lifetime — the per-call dedicated thread is their
                # isolation instead
                resp = _StreamBody(result, spec.response_serializer,
                                   None if payload_iter is not None
                                   else pool, _cleanup)
            else:
                resp, _ = spec.response_serializer.encode(result)
                span.response_size = len(resp)
        except Exception as e:
            error_code = errors.EINTERNAL
            text = f"{type(e).__name__}: {e}"
        finally:
            if not streaming:
                _finish(error_code)
        return resp, error_code, text


# ---- global server registry (builtin services enumerate servers) ----

_servers: list[Server] = []
_servers_mu = threading.Lock()
# servers that installed the process-wide usercode budget/inline policy;
# the native flags are cleared only when the last owner joins
_usercode_policy_owners: set[int] = set()

# process-wide refcounts for the native method registry (several servers
# may expose the same (service, method); the registry is global)
_native_reg: dict[tuple[str, str], int] = {}
_native_reg_mu = threading.Lock()


def _native_method_register(key: tuple[str, str]) -> None:
    with _native_reg_mu:
        n = _native_reg.get(key, 0)
        _native_reg[key] = n + 1
        if n == 0:
            Transport.register_python_method(*key)


def _native_method_unregister(key: tuple[str, str]) -> None:
    with _native_reg_mu:
        n = _native_reg.get(key, 0)
        if n <= 1:
            _native_reg.pop(key, None)
            Transport.unregister_method(*key)
        else:
            _native_reg[key] = n - 1


def _register_server(s: Server) -> None:
    with _servers_mu:
        _servers.append(s)


def _unregister_server(s: Server) -> None:
    with _servers_mu:
        if s in _servers:
            _servers.remove(s)


def list_servers() -> list[Server]:
    with _servers_mu:
        return list(_servers)
