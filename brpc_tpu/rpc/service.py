"""Service definition API.

The reference consumes protobuf-generated service classes
(Server::AddService, server.h:376); our services are plain Python classes
whose RPC methods are marked with @method, declaring request/response
serializers ("raw" | "json" | "pb" | "tensor" | "pickle", see
serialization.py).  A protobuf service works by passing message classes:

    class Echo(Service):
        @method(request="json", response="json")
        def Echo(self, cntl, req):
            return {"msg": req["msg"]}
"""
from __future__ import annotations

import inspect
from typing import Any, Callable

from brpc_tpu.rpc.serialization import PbSerializer, get_serializer


class MethodSpec:
    __slots__ = ("name", "fn", "request_serializer", "response_serializer",
                 "max_concurrency")

    def __init__(self, name, fn, request_serializer, response_serializer,
                 max_concurrency=None):
        self.name = name
        self.fn = fn
        self.request_serializer = request_serializer
        self.response_serializer = response_serializer
        self.max_concurrency = max_concurrency


def method(request: str | Any = "raw", response: str | Any = "raw",
           request_class=None, response_class=None, max_concurrency=None):
    """Mark an RPC method.  request/response name a serializer; pb message
    classes may be given via request_class/response_class."""

    def deco(fn: Callable):
        req_s = PbSerializer(request_class) if request_class is not None \
            else get_serializer(request)
        res_s = PbSerializer(response_class) if response_class is not None \
            else get_serializer(response)
        fn.__rpc_spec__ = MethodSpec(fn.__name__, fn, req_s, res_s,
                                     max_concurrency)
        return fn

    return deco


class Service:
    """Base class; NAME defaults to the class name (full service name in
    method maps, like FindMethodPropertyByFullName in the reference)."""

    NAME: str | None = None

    @classmethod
    def service_name(cls) -> str:
        return cls.NAME or cls.__name__

    def rpc_methods(self) -> dict[str, MethodSpec]:
        out = {}
        for name, member in inspect.getmembers(self, callable):
            spec = getattr(member, "__rpc_spec__", None)
            if spec is None:
                # an UNdecorated override still implements the rpc when a
                # base class declared it (@method in the generated Base,
                # plain `def Add(...)` in the subclass — the protoc
                # codegen pattern): inherit the base's spec, bind the
                # subclass's implementation
                for klass in type(self).__mro__[1:]:
                    base_fn = klass.__dict__.get(name)
                    spec = getattr(base_fn, "__rpc_spec__", None)
                    if spec is not None:
                        break
            if spec is not None:
                out[spec.name] = MethodSpec(spec.name, member,
                                            spec.request_serializer,
                                            spec.response_serializer,
                                            spec.max_concurrency)
        return out
