"""Streaming RPC — ordered message pipe with credit-window flow control.

Reference: stream.{h,cpp}, stream_impl.h, policy/streaming_rpc_protocol.cpp
(SURVEY.md §5.7): a stream piggybacks on an ordinary RPC (stream settings in
the request meta, accepted server-side), then DATA frames flow with a
sliding window — the writer blocks once `produced - remote_consumed` exceeds
the buffer; the consumer sends CONSUMED feedback frames that advance the
window.  Frames ARRIVE in order (one TCP socket per connection) but the
native core dispatches each parsed message onto the work-stealing executor,
so handler dispatch may be reordered — the stream_seq/reorder layer below
restores write order (the reference's per-stream ExecutionQueue).

ONE stream abstraction for host bytes AND device tensors: `write()` also
accepts jax device arrays.  When the peer has an ICI-reachable device, the
tensor payload slides under the socket exactly the way the reference
slides RDMA under Socket::StartWrite (socket.cpp:1751-1757, the
CutFromIOBufList swap): blocks stage on device, ride IciEndpoint's
credit-windowed transfer (brpc_tpu/ici/rail.py), and the DATA frame
carries only a claim ticket — CONSUMED feedback stays on the host socket
either way, and `rail.host_copy_count()` proves the zero-copy path.  A
peer without a reachable device gets the tensor-serializer fallback
(host bytes, still arrays at the far end).

Sizing max_buf_size: the window is a bandwidth-delay product.  Credit
releases cost one delivery round-trip (DATA frame -> claim -> handler ->
CONSUMED), so sustained throughput is capped at max_buf_size / RTT —
size the window to target_bandwidth x link RTT.  On a directly attached
chip the RTT is ~us and the default is generous; over a tunneled or DCN
link (tens of ms) a 256MB window caps the pipe at single-digit GB/s
while 1GB restores it (measured on the r5 dev tunnel: 2 -> 34 GB/s).
The rail's own credit window self-sizes the same way (rail._window_for).
"""
from __future__ import annotations

import itertools
import logging
import threading
from typing import Callable, Optional

from brpc_tpu import errors, fault
from brpc_tpu.bvar import Adder
from brpc_tpu.rpc import meta as M
from brpc_tpu.rpc.transport import Transport

# hostile-peer shed events, on /vars next to EOVERCROWDED (a bound that
# fires silently is a bound operators can't see tripping)
reorder_replays_dropped = Adder("stream_reorder_replays_dropped")
reorder_overflow_closes = Adder("stream_reorder_overflow_closes")
# Bytes of dropped replayed/duplicate DATA frames (ADVICE r5): dropped
# duplicates are never acked, so their bytes permanently consume the
# SENDER's credit window.  Intentional for hostile peers on today's
# no-retransmit transport — but if transport-level redelivery is ever
# introduced, a wedged writer's credit shortfall must be explainable by
# this counter instead of being silent (the chaos drain test asserts
# exactly that).
reorder_replay_bytes_dropped = Adder("stream_reorder_replay_bytes_dropped")

DEFAULT_BUF_SIZE = 2 * 1024 * 1024

_stream_ids = itertools.count(1)


class StreamHandler:
    """Reference StreamInputHandler (stream.h:41-44)."""

    def on_received_messages(self, stream: "Stream", messages: list[bytes]) -> None:
        pass

    def on_idle_timeout(self, stream: "Stream") -> None:
        pass

    def on_closed(self, stream: "Stream") -> None:
        pass


class _FnHandler(StreamHandler):
    def __init__(self, fn, on_closed=None):
        self._fn = fn
        self._on_closed = on_closed

    def on_received_messages(self, stream, messages):
        for m in messages:
            self._fn(stream, m)

    def on_closed(self, stream):
        if self._on_closed is not None:
            self._on_closed(stream)


class Stream:
    """Each side owns a local id (registry key) and learns the peer's id —
    outgoing frames are addressed to the peer's local id, exactly how the
    reference exchanges stream ids through StreamSettings in the request/
    response meta (streaming_rpc_meta.proto)."""

    def __init__(self, stream_id: int, handler: Optional[StreamHandler],
                 max_buf_size: int = DEFAULT_BUF_SIZE, device=None):
        self.stream_id = stream_id               # local id
        self.remote_id: Optional[int] = None     # peer's local id
        self.handler = handler
        self.max_buf_size = max_buf_size
        # tensor rail endpoints: `device` is where WE receive tensor
        # payloads (advertised to the peer in the settings exchange,
        # F_SDEV); `peer_device` is where the PEER receives — learned
        # from its settings/rail map, None = host-serialize fallback
        self.device = device
        self.peer_device = None
        # The WRITER's window size, learned from the StreamSettings exchange:
        # feedback must fire well before the peer's window fills, regardless
        # of our own buffer size (a 2MB receiver facing a 256KB writer would
        # otherwise never send feedback and deadlock the writer).
        self.peer_buf_size: Optional[int] = None
        self._sid: Optional[int] = None          # bound host connection
        self._mu = threading.Lock()
        self._window_cv = threading.Condition(self._mu)
        self._produced = 0
        self._remote_consumed = 0
        self._consumed_local = 0                 # receiver side
        self._last_feedback = 0
        # writes before binding: (seq, "bytes"|"tensor", payload)
        self._pending: list[tuple[int, str, object]] = []
        self._closed = False
        self._close_sent = False
        # Ordered delivery (the reference's per-stream ExecutionQueue,
        # stream_impl.h:133): our native core dispatches each parsed message
        # onto the work-stealing executor, so DATA frames for one stream may
        # be PROCESSED out of order even though they ARRIVE in order.  The
        # writer numbers frames (stream_seq, 1-based) and the receiver
        # reorders + serializes handler delivery with a drain loop.
        self._send_seq = 1
        self._recv_next = 1
        self._reorder: dict[int, bytes] = {}
        self._reorder_bytes = 0
        self._close_seq: Optional[int] = None
        self._delivering = False
        # Tensor write coalescing: rail-bound writes go through a
        # per-stream sender thread that drains its queue in batches, so N
        # back-to-back stream.write(array) calls become ONE batched
        # device dispatch (rail.ship_many) instead of N — on a tunneled
        # chip each dispatch costs a host round-trip, which made
        # per-message shipping the whole streaming-tensor cost.  Frames
        # still go out one per message (the receiver's seq-reorder layer
        # already tolerates any arrival order).
        self._tq = None
        self._tq_thread: Optional[threading.Thread] = None
        self._tq_closing = False

    # ---- binding (the RPC established the host connection) ----

    def bind(self, sid: int) -> None:
        with self._mu:
            self._sid = sid
        self._maybe_flush()

    def set_remote(self, remote_id: int) -> None:
        with self._mu:
            self.remote_id = remote_id
        self._maybe_flush()

    def _maybe_flush(self) -> None:
        with self._mu:
            if self._sid is None or self.remote_id is None:
                return
            pending, self._pending = self._pending, []
        for seq, kind, payload in pending:
            if kind == "bytes":
                self._send_data(payload, seq)
            else:
                self._send_tensor(payload, seq)

    @property
    def connected(self) -> bool:
        return self._sid is not None and self.remote_id is not None

    @property
    def closed(self) -> bool:
        return self._closed

    # ---- writer side (StreamWrite, stream.cpp:721/274) ----

    def write(self, data, timeout_s: float | None = 10.0) -> None:
        """Write one message: host bytes OR a jax device array (or a
        list/tuple of them).  Blocks while the window is full; raises
        RpcError(EAGAIN-like) on timeout, EEOF if closed.  Device
        payloads count their device nbytes against the same window."""
        if isinstance(data, (bytes, bytearray, memoryview)):
            kind, payload, nbytes = "bytes", bytes(data), len(data)
        else:
            from brpc_tpu.ici import rail
            if not rail.railable(data):
                raise TypeError(
                    "stream write takes bytes or jax device arrays, "
                    f"not {type(data).__name__}")
            arrays = data if isinstance(data, (list, tuple)) else [data]
            kind, payload = "tensor", data
            nbytes = sum(a.nbytes for a in arrays)
        if self._closed or self._close_sent:
            raise errors.RpcError(errors.EEOF, "stream closed")
        with self._window_cv:
            deadline = None
            while (self._produced + nbytes - self._remote_consumed
                   > self.max_buf_size):
                if self._closed:
                    raise errors.RpcError(errors.EEOF, "stream closed")
                import time
                if deadline is None:
                    if timeout_s is None:
                        deadline = float("inf")
                    else:
                        deadline = time.monotonic() + timeout_s
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise errors.RpcError(
                        errors.EOVERCROWDED,
                        f"stream window full ({self.max_buf_size}B)")
                self._window_cv.wait(min(remaining, 1.0))
            self._produced += nbytes
            seq = self._send_seq
            self._send_seq += 1
            if self._sid is None or self.remote_id is None:
                self._pending.append((seq, kind, payload))
                return
        if kind == "bytes":
            self._send_data(payload, seq)
        else:
            self._send_tensor(payload, seq)

    def _send_data(self, data: bytes, seq: int) -> None:
        rc = Transport.instance().write_frame(
            self._sid, M.RpcMeta.encode_stream_data(self.remote_id, seq),
            data)
        if rc != 0:
            self._on_closed_internal()

    def _send_tensor(self, obj, seq: int) -> None:
        """StreamWrite for device payloads — the RDMA slide-under
        (socket.cpp:1751-1757): with a reachable peer device the tensors
        move HBM→HBM through the rail and the socket frame carries only
        the claim ticket; otherwise the tensor serializer produces a host
        fallback frame that still rebuilds arrays at the far end.

        Rail-bound writes are queued to the per-stream sender thread so
        adjacent messages share one batched dispatch (ship_many); the
        no-device fallback serializes inline as before.  Enqueue order
        vs the close sentinel is serialized under _mu: a write that loses
        the race to close() sends inline instead of landing in a queue no
        thread will drain."""
        if self.peer_device is not None:
            self._ensure_tensor_sender()
            with self._mu:
                closing = self._tq_closing
                if not closing:
                    self._tq.put((seq, obj))
            if closing:
                self._send_tensor_fallback(obj, seq)
            return
        self._send_tensor_fallback(obj, seq)

    def _send_tensor_fallback(self, obj, seq: int) -> None:
        """Host-serialized tensor frame — the no-reachable-device shape,
        also the escape hatch when the rail or the sender queue is gone."""
        from brpc_tpu.ici import rail
        rail.rail_fallbacks.add(1)
        from brpc_tpu.rpc.serialization import get_serializer
        meta = M.RpcMeta(msg_type=M.MSG_STREAM_DATA,
                         stream_id=self.remote_id, stream_seq=seq)
        body, meta.tensor_header = get_serializer("tensor").encode(obj)
        rc = Transport.instance().write_frame(self._sid, meta.encode(), body)
        if rc != 0:
            self._on_closed_internal()

    def _ensure_tensor_sender(self) -> None:
        if self._tq is None:
            with self._mu:
                if self._tq is None:
                    import queue as _qm
                    import weakref
                    q = _qm.Queue()
                    # the thread must NOT keep the Stream alive: it holds
                    # only a weakref and exits when the stream is gone —
                    # an abandoned stream (no close(), no peer CLOSE) must
                    # stay garbage-collectable, not pin a thread forever
                    t = threading.Thread(
                        target=_tensor_send_loop,
                        args=(weakref.ref(self), q),
                        daemon=True, name=f"stream-tsend-{self.stream_id}")
                    self._tq = q
                    self._tq_thread = t
                    t.start()

    def _flush_tensor_sender(self) -> None:
        """Drain queued tensor writes and stop the sender — close() must
        not race CLOSE past data still sitting in the queue.  _tq_closing
        is set under _mu BEFORE the sentinel goes in, so any concurrent
        write either precedes the sentinel (flushed here) or observes
        _tq_closing and sends inline."""
        t = self._tq_thread
        if t is None or t is threading.current_thread():
            return
        with self._mu:
            self._tq_closing = True
            self._tq.put(None)
        t.join(timeout=30)
        self._tq_thread = None

    # ---- receiver side ----

    def _on_data(self, payload, nbytes: int, seq: int) -> None:
        if seq == 0:
            # unsequenced peer (pre-stream_seq wire format): deliver in
            # arrival order, mirroring the seq==0 CLOSE fallback
            if self.handler is not None:
                try:
                    self.handler.on_received_messages(self, [payload])
                except Exception:
                    logging.exception("stream handler raised")
            self._ack(nbytes)
            return
        with self._mu:
            if seq < self._recv_next or seq in self._reorder:
                # replay of a delivered or in-flight seq: a sub-
                # _recv_next entry would park in the dict FOREVER (the
                # drain only pops forward), so a replaying peer could
                # grow it without bound — drop duplicates outright.
                # NOTE: dropped bytes are never acked, so they consume
                # the sender's credit window permanently — counted so a
                # credit shortfall under (future) redelivery is visible
                # on /vars rather than a silent writer wedge.
                reorder_replays_dropped.add(1)
                reorder_replay_bytes_dropped.add(nbytes)
                return
            self._reorder[seq] = (payload, nbytes)
            self._reorder_bytes += nbytes
            # a CORRECT peer can never have more unacked bytes in flight
            # than the WRITER's credit window (peer_buf_size, learned in
            # the settings exchange; our own max_buf_size when the peer
            # is bigger-bounded or unknown); a writer ignoring the
            # window (or spraying far-future seqs that can never drain)
            # is a protocol violation, not backpressure — close before
            # the buffer becomes a memory DoS (the h2 header-block/
            # frame-bound discipline, applied to the stream reorder
            # buffer).  2x allows device payloads whose nbytes
            # accounting straddles the window.
            window = max(self.max_buf_size, self.peer_buf_size or 0)
            overflow = self._reorder_bytes > 2 * window + (64 << 10)
        if overflow:
            reorder_overflow_closes.add(1)
            logging.warning("stream %d: reorder buffer exceeded 2x the "
                            "credit window; closing (protocol violation)",
                            self.stream_id)
            # tell the live peer (seq 0 = immediate close on receipt) so
            # its writer fails EEOF instead of blocking out its window
            # against a stream that no longer exists
            if self._sid is not None and self.remote_id is not None:
                try:
                    Transport.instance().write_frame(
                        self._sid,
                        M.RpcMeta(msg_type=M.MSG_STREAM_CLOSE,
                                  stream_id=self.remote_id).encode())
                except Exception:
                    pass
            self._on_closed_internal()
            return
        self._drain()

    def _on_close_frame(self, seq: int) -> None:
        if seq == 0:
            # pre-stream_seq peer compat — immediate close
            self._on_closed_internal()
            return
        with self._mu:
            # min(): a duplicate CLOSE with a higher seq must not raise the
            # latch past what data seqs can ever satisfy
            if self._close_seq is None or seq < self._close_seq:
                self._close_seq = seq
        self._drain()

    def _drain(self) -> None:
        """Deliver consecutive frames; only one thread drains at a time
        (per-stream ExecutionQueue semantics)."""
        with self._mu:
            if self._delivering:
                return
            self._delivering = True
        while True:
            with self._mu:
                ready: list = []
                ready_bytes = 0
                while self._recv_next in self._reorder:
                    payload, nbytes = self._reorder.pop(self._recv_next)
                    self._reorder_bytes -= nbytes
                    ready.append(payload)
                    ready_bytes += nbytes
                    self._recv_next += 1
                close_now = (self._close_seq is not None
                             and self._recv_next >= self._close_seq)
                if not ready and not close_now:
                    self._delivering = False
                    return
            if ready and self.handler is not None:
                try:
                    self.handler.on_received_messages(self, ready)
                except Exception:
                    # a raising handler must not wedge the drain loop
                    # (_delivering would stay True forever)
                    logging.exception("stream handler raised")
            if ready:
                self._ack(ready_bytes)
            if close_now:
                with self._mu:
                    self._delivering = False
                self._on_closed_internal()
                return

    def _ack(self, nbytes: int) -> None:
        with self._mu:
            self._consumed_local += nbytes
            threshold = min(self.max_buf_size,
                            self.peer_buf_size or self.max_buf_size) // 2
            send_feedback = (self._consumed_local - self._last_feedback
                             >= max(1, threshold))
            if send_feedback:
                self._last_feedback = self._consumed_local
        if send_feedback and self._sid is not None and \
                self.remote_id is not None:
            if fault.ENABLED and fault.hit(
                    "stream.feedback", stream_id=self.stream_id) is not None:
                # injected feedback loss: the sender's credit stays
                # consumed until the NEXT threshold crossing — offsets
                # are cumulative, so one lost frame delays credit return
                # rather than leaking it
                return
            meta = M.RpcMeta(msg_type=M.MSG_STREAM_FEEDBACK,
                             stream_id=self.remote_id,
                             stream_offset=self._consumed_local)
            Transport.instance().write_frame(self._sid, meta.encode())

    def _on_feedback(self, consumed: int) -> None:
        with self._window_cv:
            self._remote_consumed = max(self._remote_consumed, consumed)
            self._window_cv.notify_all()

    def _on_closed_internal(self) -> None:
        with self._window_cv:
            already = self._closed
            self._closed = True
            self._window_cv.notify_all()
        if not already and self._tq is not None:
            self._tq.put(None)    # stop the tensor sender (it may be us)
        if not already and self.handler is not None:
            self.handler.on_closed(self)
        StreamRegistry.instance().remove(self.stream_id)

    def close(self) -> None:
        with self._mu:
            if self._closed or self._close_sent:
                return
            self._close_sent = True
        self._flush_tensor_sender()
        if self._sid is not None and self.remote_id is not None:
            with self._mu:
                seq = self._send_seq
                self._send_seq += 1
            # sequenced CLOSE: the peer closes only after delivering every
            # DATA frame written before close()
            meta = M.RpcMeta(msg_type=M.MSG_STREAM_CLOSE,
                             stream_id=self.remote_id, stream_seq=seq)
            Transport.instance().write_frame(self._sid, meta.encode())
        self._on_closed_internal()


def _tensor_send_loop(wref, q) -> None:
    """Per-stream tensor sender (module-level: holds NO strong reference
    to the Stream between batches).  Exits on the close sentinel, when
    the stream dies, or when the weakref clears — whichever comes first."""
    import queue as _qm
    from brpc_tpu.ici import rail
    while True:
        try:
            item = q.get(timeout=5.0)
        except _qm.Empty:
            s = wref()
            if s is None or s._closed:
                return
            del s
            continue
        if item is None:
            return
        batch = [item]
        stop = False
        while True:
            try:
                nxt = q.get_nowait()
            except _qm.Empty:
                break
            if nxt is None:
                stop = True   # flush what's collected, then exit
                break
            batch.append(nxt)
        s = wref()
        if s is None or s._closed:
            # stream gone / transport dead: nothing was shipped yet for
            # this batch, so dropping it leaks no tickets
            return
        tickets = None
        try:
            tickets = rail.ship_many([obj for _, obj in batch],
                                     s.peer_device)
        except Exception:
            logging.exception("stream rail ship failed; host fallback")
        if tickets is not None:
            # ticket frames are tiny (meta only, empty bodies): ship the
            # whole batch as ONE socket write — one ctypes crossing and
            # one write-stack push instead of len(batch), ordering
            # preserved.  Tiny frames can never trip the per-write
            # EOVERCROWDED bound the way coalesced big bodies would.
            frames = []
            for k, (seq, obj) in enumerate(batch):
                frames.append((M.RpcMeta.encode_stream_data(
                    s.remote_id, seq, ticket=tickets[k],
                    src_dev=str(rail.source_device(obj).id)), b""))
            if Transport.instance().write_frames(s._sid, frames) != 0:
                for t in tickets:       # atomic pops: no double-free
                    rail.withdraw(t)
                s._on_closed_internal()
                return
        else:
            # host fallback: bodies are full serialized tensors — write
            # per frame so each passes the overcrowded bound on its own
            # and no giant contiguous join is materialized
            from brpc_tpu.rpc.serialization import get_serializer
            for seq, obj in batch:
                meta = M.RpcMeta(msg_type=M.MSG_STREAM_DATA,
                                 stream_id=s.remote_id, stream_seq=seq)
                rail.rail_fallbacks.add(1)
                body, meta.tensor_header = \
                    get_serializer("tensor").encode(obj)
                if Transport.instance().write_frame(
                        s._sid, meta.encode(), body) != 0:
                    s._on_closed_internal()
                    return
        if stop:
            return
        del s    # drop the strong ref while parked in q.get


class StreamRegistry:
    _instance = None
    _lock = threading.Lock()

    @classmethod
    def instance(cls) -> "StreamRegistry":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def __init__(self):
        self._streams: dict[int, Stream] = {}
        self._mu = threading.Lock()

    def register(self, stream: Stream) -> None:
        with self._mu:
            self._streams[stream.stream_id] = stream

    def get(self, stream_id: int) -> Optional[Stream]:
        with self._mu:
            return self._streams.get(stream_id)

    def remove(self, stream_id: int) -> None:
        with self._mu:
            self._streams.pop(stream_id, None)

    def count(self) -> int:
        with self._mu:
            return len(self._streams)

    def on_socket_failed(self, sid: int) -> None:
        """The bound host connection died: every stream riding it is
        unrecoverable — DATA frames can neither arrive nor leave — so
        each one closes NOW and its handler's ``on_closed`` fires.
        Without this, a stream whose peer process died silently (no
        CLOSE frame) waits forever: the cluster router's failover
        (ISSUE 8) depends on learning about a dead replica at socket
        speed, not at application-timeout speed."""
        with self._mu:
            dead = [s for s in self._streams.values() if s._sid == sid]
        for s in dead:
            s._on_closed_internal()

    @staticmethod
    def _withdraw_ticket(meta: M.RpcMeta) -> None:
        """An undeliverable DATA frame's rail ticket must still be
        withdrawn, or its HBM blocks sit pinned until the registry TTL
        fires — shared by the dead-stream path and the injected-DROP
        path, so the discipline lives in one place."""
        if meta.msg_type == M.MSG_STREAM_DATA and meta.user_fields \
                and meta.user_fields.get(M.F_TICKET):
            from brpc_tpu.ici import rail
            rail.withdraw(meta.user_fields[M.F_TICKET])

    def on_frame(self, sid: int, meta: M.RpcMeta, body) -> None:
        # meta.stream_id addresses the RECEIVER's local stream.
        dup = False
        if fault.ENABLED:
            # ctx carries msg_type AND stream_seq so plans can scope
            # rules to the frames a kind is meaningful for — DUP in
            # particular only duplicates SEQUENCED data (the seq==0
            # compat branch delivers in arrival order with no dedup);
            # scope DUP rules with match=... on msg_type/stream_seq or
            # the firing is a counted no-op on other frames
            f = fault.hit("stream.frame", stream_id=meta.stream_id,
                          msg_type=meta.msg_type,
                          stream_seq=meta.stream_seq)
            if f is not None:
                if f.kind == fault.DROP:
                    self._withdraw_ticket(meta)
                    return
                dup = (f.kind == fault.DUP
                       and meta.msg_type == M.MSG_STREAM_DATA
                       and meta.stream_seq != 0)
        s = self.get(meta.stream_id)
        if s is None:
            self._withdraw_ticket(meta)
            return
        if s._sid is None:
            s.bind(sid)
        if meta.msg_type == M.MSG_STREAM_DATA:
            try:
                payload, nbytes = _decode_data_frame(meta, body)
            except Exception:
                # an expired ticket / corrupt tensor header poisons the
                # SEQUENCE (a message is unrecoverably lost): close
                logging.exception("stream data frame undecodable")
                s._on_closed_internal()
                return
            s._on_data(payload, nbytes, meta.stream_seq)
            if dup:
                # injected transport-level redelivery: the duplicate must
                # be dropped by the reorder layer and its bytes counted
                # (reorder_replay_bytes_dropped), never delivered twice
                s._on_data(payload, nbytes, meta.stream_seq)
        elif meta.msg_type == M.MSG_STREAM_FEEDBACK:
            s._on_feedback(meta.stream_offset)
        elif meta.msg_type == M.MSG_STREAM_CLOSE:
            s._on_close_frame(meta.stream_seq)


def _decode_data_frame(meta: M.RpcMeta, body):
    """One DATA frame -> (payload, window_bytes).  Three wire shapes:
    rail ticket (device arrays HBM->HBM, zero host copies), tensor
    header (host-serialized arrays, the no-reachable-device fallback),
    plain bytes."""
    if meta.user_fields and meta.user_fields.get(M.F_TICKET):
        from brpc_tpu.ici import rail
        obj = rail.claim(meta.user_fields[M.F_TICKET])
        arrays = obj if isinstance(obj, list) else [obj]
        return obj, sum(a.nbytes for a in arrays)
    if meta.tensor_header:
        from brpc_tpu.rpc.serialization import get_serializer
        obj = get_serializer("tensor").decode(body.to_bytes(),
                                              meta.tensor_header)
        arrays = obj if isinstance(obj, (list, tuple)) else [obj]
        return obj, sum(a.nbytes for a in arrays)
    data = body.to_bytes()
    return data, len(data)


def stream_create(cntl, handler: StreamHandler | Callable | None = None,
                  max_buf_size: int = DEFAULT_BUF_SIZE,
                  device=None) -> Stream:
    """Client side: create a stream riding the next RPC issued with `cntl`
    (reference StreamCreate, stream.cpp:772).  `device` is where THIS side
    receives tensor payloads (advertised to the peer); the peer's receive
    device is learned from the rail map / settings response."""
    if callable(handler) and not isinstance(handler, StreamHandler):
        handler = _FnHandler(handler)
    s = Stream(next(_stream_ids), handler, max_buf_size, device=device)
    StreamRegistry.instance().register(s)
    cntl._stream = s
    return s


def stream_accept(cntl, handler: StreamHandler | Callable | None = None,
                  max_buf_size: int = DEFAULT_BUF_SIZE,
                  device=None) -> Stream:
    """Server side, inside a handler: accept the peer's stream
    (reference StreamAccept, stream.cpp:813).  `device` is this side's
    tensor receive device (advertised back in the settings response)."""
    meta = cntl.request_meta
    if meta is None or meta.stream_id == 0:
        raise errors.RpcError(errors.EREQUEST, "no stream attached")
    if callable(handler) and not isinstance(handler, StreamHandler):
        handler = _FnHandler(handler)
    s = Stream(next(_stream_ids), handler, max_buf_size, device=device)
    s.set_remote(meta.stream_id)     # client's local id from the request
    sbuf = meta.user_fields.get("sbuf")
    if sbuf:
        s.peer_buf_size = int(sbuf)
    sdev = meta.user_fields.get(M.F_SDEV)
    if sdev:
        # the client's advertised receive device: the process token in
        # the advert makes this fail closed for out-of-process peers,
        # whose rail tickets could never be claimed
        from brpc_tpu.ici import rail as _rail
        s.peer_device = _rail.device_from_wire(sdev)
    s.bind(cntl.peer_sid)
    StreamRegistry.instance().register(s)
    cntl._stream = s                 # response meta carries our local id
    return s
