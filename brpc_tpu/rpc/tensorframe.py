"""tensorframe — the mixed-payload binary wire format (ISSUE 13).

PR 12 published the honest number: a 64-key PS.Lookup costs ~24ms
through the full RPC stack vs ~500us as one compiled collective —
dominated by JSON rows over sockets, exactly the serialization + copy
overhead "RPC Considered Harmful" (PAPERS.md) measures.  bRPC's answer
is the baidu_std attachment riding IOBuf untouched (PAPER.md L3/L4);
this module is ours: a self-framed binary body whose tensor bytes are
DECODED AS VIEWS — ``np.frombuffer`` straight over the IOBuf-backed
memoryview the transport hands up, zero host copies through transport
slicing (the ``tensor_host_encodes/decodes`` counters of the old
tensor serializer never move on this path).

Frame layout (little-endian throughout; golden-pinned by
tests/test_tensorframe.py so it cannot drift silently)::

    magic  b"TFr1"                      (4 bytes)
    u8     n_fields                     (<= MAX_FIELDS)
    per field:
      u8   name_len  (1..MAX_NAME), name bytes (ascii)
      u8   kind      1=int 2=float 3=bool 4=str 5=bytes 6=tensor
      int    -> <q        float -> <d        bool -> u8 (0|1)
      str    -> <I len (<= MAX_INLINE) + utf-8 bytes
      bytes  -> <I len (<= MAX_INLINE) + raw bytes
      tensor -> u8 dtype_code, u8 ndim (<= MAX_NDIM), ndim * <Q dims
    tensor arena: every tensor's C-order bytes, packed in field order,
    immediately after the field table.  The arena must be consumed
    EXACTLY — trailing garbage is a malformed frame, not padding.

The decode is BOUNDED the way ``rpc/compact.py`` is bounded-depth:
every header read is bounds-checked, dtypes come from a closed enum
(never ``np.dtype(hostile_string)``), shape products are computed in
exact Python ints and checked against the remaining arena BEFORE any
allocation — a frame claiming 2**60 elements raises ``ValueError``
without allocating a byte.  Malformed frames surface as ``ValueError``,
which the server's decode phase maps to a clean ``EREQUEST``.

Scalars/strings ride inline because PS requests carry a handful of
them (update_id, versions); anything array-shaped rides the tensor
slot.  The PS surface (psserve) is the first adopter; Serving.Score
and the migrate plane are natural follow-ons (see README).
"""
from __future__ import annotations

import struct
from typing import Any, Union

import numpy as np

from brpc_tpu.bvar import Adder

MAGIC = b"TFr1"

MAX_FIELDS = 64
MAX_NAME = 64
MAX_NDIM = 8
MAX_INLINE = 1 << 20          # inline str/bytes cap (tensors are arena)

KIND_INT = 1
KIND_FLOAT = 2
KIND_BOOL = 3
KIND_STR = 4
KIND_BYTES = 5
KIND_TENSOR = 6

# closed dtype enum: decode NEVER parses a dtype string off the wire
# (np.dtype(str) ast-parses some specs — the tensor-serializer fuzz
# target found SyntaxError paths in there)
_DTYPE_BY_CODE = {
    1: np.dtype("<i8"),
    2: np.dtype("<f4"),
    3: np.dtype("<f8"),
    4: np.dtype("<i4"),
    5: np.dtype("|u1"),
    6: np.dtype("|b1"),
    7: np.dtype("<u8"),
    8: np.dtype("<f2"),
}
_CODE_BY_DTYPE = {dt: c for c, dt in _DTYPE_BY_CODE.items()}

FRAME_ENCODES = Adder("tensorframe_encodes")
FRAME_DECODES = Adder("tensorframe_decodes")
# encode-side forced materializations beyond the single frame-assembly
# join (non-contiguous / byte-swapped arrays a caller snuck in); the
# loopback bench pins this at zero for the PS surface
FRAME_HOST_COPIES = Adder("tensorframe_host_copies")


def is_frame(buf) -> bool:
    """Cheap magic sniff (negotiation helpers, tools)."""
    return bytes(buf[:4]) == MAGIC if buf is not None and len(buf) >= 4 \
        else False


def _tensor_code(a: np.ndarray) -> int:
    dt = a.dtype.newbyteorder("<") if a.dtype.byteorder == ">" \
        else a.dtype
    code = _CODE_BY_DTYPE.get(np.dtype(dt))
    if code is None:
        raise TypeError(
            f"tensorframe has no wire code for dtype {a.dtype}; "
            f"supported: {sorted(str(d) for d in _CODE_BY_DTYPE)}")
    return code


def encode_frame(fields: dict) -> bytes:
    """One frame from ``{name: int|float|bool|str|bytes|ndarray}``.

    Kind is chosen from the Python type; numpy arrays (any rank,
    including 0-d) take the tensor slot.  Returns the complete frame
    body (header + tensor arena) as one bytes object — a single join,
    no per-element conversion, no float64 round-trip."""
    if len(fields) > MAX_FIELDS:
        raise ValueError(f"{len(fields)} fields > MAX_FIELDS={MAX_FIELDS}")
    hdr: list[bytes] = [MAGIC, struct.pack("<B", len(fields))]
    arena: list = []
    for name, v in fields.items():
        nb = str(name).encode("ascii")
        if not 1 <= len(nb) <= MAX_NAME:
            raise ValueError(f"field name {name!r} length must be "
                             f"1..{MAX_NAME}")
        hdr.append(struct.pack("<B", len(nb)))
        hdr.append(nb)
        if isinstance(v, bool):          # before int: bool IS int
            hdr.append(struct.pack("<BB", KIND_BOOL, 1 if v else 0))
        elif isinstance(v, (int, np.integer)):
            hdr.append(struct.pack("<Bq", KIND_INT, int(v)))
        elif isinstance(v, (float, np.floating)):
            hdr.append(struct.pack("<Bd", KIND_FLOAT, float(v)))
        elif isinstance(v, str):
            b = v.encode("utf-8")
            if len(b) > MAX_INLINE:
                raise ValueError(f"str field {name!r} exceeds inline cap")
            hdr.append(struct.pack("<BI", KIND_STR, len(b)))
            hdr.append(b)
        elif isinstance(v, (bytes, bytearray, memoryview)):
            b = bytes(v)
            if len(b) > MAX_INLINE:
                raise ValueError(f"bytes field {name!r} exceeds inline cap")
            hdr.append(struct.pack("<BI", KIND_BYTES, len(b)))
            hdr.append(b)
        elif isinstance(v, np.ndarray):
            code = _tensor_code(v)
            if v.ndim > MAX_NDIM:
                raise ValueError(f"tensor field {name!r} ndim {v.ndim} > "
                                 f"{MAX_NDIM}")
            body = v
            if not body.flags.c_contiguous or \
                    body.dtype != _DTYPE_BY_CODE[code]:
                # the one place encode may copy: strided or big-endian
                # input (counted so the zero-copy claim stays testable)
                body = np.ascontiguousarray(body,
                                            dtype=_DTYPE_BY_CODE[code])
                FRAME_HOST_COPIES.add(1)
            hdr.append(struct.pack(f"<BBB{v.ndim}Q", KIND_TENSOR, code,
                                   v.ndim, *v.shape))
            # memoryview: the final join reads the array's buffer
            # directly — no .tobytes() materialization per tensor
            arena.append(memoryview(body).cast("B"))
        else:
            raise TypeError(f"field {name!r}: unsupported type {type(v)}")
    FRAME_ENCODES.add(1)
    return b"".join(hdr + arena)


class _Cursor:
    """Bounds-checked reader over the frame header."""

    __slots__ = ("buf", "off", "end")

    def __init__(self, buf, off: int, end: int):
        self.buf = buf
        self.off = off
        self.end = end

    def take(self, n: int):
        if self.off + n > self.end:
            raise ValueError("truncated tensorframe header")
        out = self.buf[self.off : self.off + n]
        self.off += n
        return out

    def unpack(self, fmt: str):
        n = struct.calcsize(fmt)
        if self.off + n > self.end:
            raise ValueError("truncated tensorframe header")
        out = struct.unpack_from(fmt, self.buf, self.off)
        self.off += n
        return out


def decode_frame(buf: Union[bytes, bytearray, memoryview]) -> dict:
    """Frame body -> ``{name: value}``.

    Tensor fields come back as numpy VIEWS over ``buf`` (zero copy —
    a memoryview straight off the transport stays pinned to its IOBuf
    blocks while any returned array references it).  Every malformed
    input raises ``ValueError`` with bounded allocation: header reads
    are bounds-checked and tensor byte counts are proven against the
    arena before any array object exists."""
    if isinstance(buf, memoryview):
        if buf.ndim != 1 or buf.itemsize != 1:
            buf = buf.cast("B")
    n = len(buf)
    if n < 5 or bytes(buf[:4]) != MAGIC:
        raise ValueError("not a tensorframe (bad magic)")
    cur = _Cursor(buf, 4, n)
    (n_fields,) = cur.unpack("<B")
    if n_fields > MAX_FIELDS:
        raise ValueError(f"{n_fields} fields > MAX_FIELDS={MAX_FIELDS}")
    out: dict[str, Any] = {}
    # pass 1 — walk the field table (inline values decode here; tensor
    # specs are recorded), bounding everything before arena math
    tensors: list[tuple[str, np.dtype, tuple, int]] = []
    arena_bytes = 0
    for _ in range(n_fields):
        (name_len,) = cur.unpack("<B")
        if not 1 <= name_len <= MAX_NAME:
            raise ValueError(f"field name length {name_len} out of "
                             f"1..{MAX_NAME}")
        try:
            name = bytes(cur.take(name_len)).decode("ascii")
        except UnicodeDecodeError as e:
            raise ValueError(f"non-ascii field name: {e}")
        if name in out or any(t[0] == name for t in tensors):
            raise ValueError(f"duplicate field {name!r}")
        (kind,) = cur.unpack("<B")
        if kind == KIND_INT:
            (out[name],) = cur.unpack("<q")
        elif kind == KIND_FLOAT:
            (out[name],) = cur.unpack("<d")
        elif kind == KIND_BOOL:
            (b,) = cur.unpack("<B")
            if b not in (0, 1):
                raise ValueError(f"bool field {name!r} byte {b} not 0|1")
            out[name] = bool(b)
        elif kind in (KIND_STR, KIND_BYTES):
            (ln,) = cur.unpack("<I")
            if ln > MAX_INLINE:
                raise ValueError(f"inline field {name!r} claims {ln} "
                                 f"bytes > cap {MAX_INLINE}")
            raw = bytes(cur.take(ln))
            if kind == KIND_STR:
                try:
                    out[name] = raw.decode("utf-8")
                except UnicodeDecodeError as e:
                    raise ValueError(f"bad utf-8 in str field "
                                     f"{name!r}: {e}")
            else:
                out[name] = raw
        elif kind == KIND_TENSOR:
            code, ndim = cur.unpack("<BB")
            dt = _DTYPE_BY_CODE.get(code)
            if dt is None:
                raise ValueError(f"unknown tensor dtype code {code}")
            if ndim > MAX_NDIM:
                raise ValueError(f"tensor ndim {ndim} > {MAX_NDIM}")
            shape = cur.unpack(f"<{ndim}Q")
            # exact Python-int element count (np.prod silently wraps);
            # bound against the whole buffer BEFORE any allocation so
            # an absurd shape product can never drive an allocation
            cnt = 1
            for d in shape:
                cnt *= int(d)
            nbytes = cnt * dt.itemsize
            if arena_bytes + nbytes > n:
                raise ValueError(
                    f"tensor field {name!r} claims {cnt} x {dt} "
                    f"({nbytes} bytes) but frame holds {n} bytes")
            arena_bytes += nbytes
            tensors.append((name, dt, shape, nbytes))
        else:
            raise ValueError(f"unknown field kind {kind}")
    # pass 2 — the arena must match the declared tensors EXACTLY
    if n - cur.off != arena_bytes:
        raise ValueError(
            f"tensor arena is {n - cur.off} bytes, field table "
            f"declares {arena_bytes}")
    pos = cur.off
    for name, dt, shape, nbytes in tensors:
        cnt = nbytes // dt.itemsize if dt.itemsize else 0
        # zero copy: a view over the caller's buffer (read-only when
        # the buffer is), reshaped to the declared shape
        out[name] = np.frombuffer(buf, dtype=dt, count=cnt,
                                  offset=pos).reshape(shape)
        pos += nbytes
    FRAME_DECODES.add(1)
    return out
