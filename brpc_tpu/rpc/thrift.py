"""Thrift framed-binary protocol — schema-free codec, client channel,
server-side service adaptor.

Reference: policy/thrift_protocol.cpp:766, thrift_message.{h,cpp}.  The
native core frames one complete thrift message per MSG_THRIFT (u32be
frame length + TBinaryProtocol payload, src/cc/net/parser.cc:parse_thrift)
delivered in per-connection FIFO order; replies additionally match on
seqid, mirroring the reference's correlation handling.

The codec is schema-free (no IDL compiler): requests are field lists,
decoded structs come back as {field_id: value} dicts.  This is the same
positional contract the reference's ThriftFramedMessage raw mode exposes
when no generated types are linked in.
"""
from __future__ import annotations

import struct
import threading
from collections import deque
from concurrent.futures import Future
from typing import Any, Callable, Optional

from brpc_tpu import errors
from brpc_tpu.rpc.transport import MSG_THRIFT, Transport

VERSION_1 = 0x80010000

# message types
MT_CALL, MT_REPLY, MT_EXCEPTION, MT_ONEWAY = 1, 2, 3, 4

# field types
T_STOP = 0
T_VOID = 1
T_BOOL = 2
T_BYTE = 3
T_DOUBLE = 4
T_I16 = 6
T_I32 = 8
T_I64 = 10
T_STRING = 11
T_STRUCT = 12
T_MAP = 13
T_SET = 14
T_LIST = 15


class ThriftError(Exception):
    """TApplicationException from the peer."""

    def __init__(self, message: str = "", etype: int = 0):
        self.etype = etype
        super().__init__(message or f"thrift exception type {etype}")


class TField:
    __slots__ = ("id", "ttype", "value")

    def __init__(self, fid: int, ttype: int, value: Any):
        self.id = fid
        self.ttype = ttype
        self.value = value


# ---- binary writer ---------------------------------------------------------

def _w_value(out: bytearray, ttype: int, v: Any) -> None:
    if ttype == T_BOOL:
        out.append(1 if v else 0)
    elif ttype == T_BYTE:
        out += struct.pack(">b", v)
    elif ttype == T_DOUBLE:
        out += struct.pack(">d", v)
    elif ttype == T_I16:
        out += struct.pack(">h", v)
    elif ttype == T_I32:
        out += struct.pack(">i", v)
    elif ttype == T_I64:
        out += struct.pack(">q", v)
    elif ttype == T_STRING:
        raw = v.encode("utf-8") if isinstance(v, str) else bytes(v)
        out += struct.pack(">i", len(raw)) + raw
    elif ttype == T_STRUCT:
        _w_struct(out, v)
    elif ttype == T_MAP:
        ktype, vtype, items = v
        out += struct.pack(">bbi", ktype, vtype, len(items))
        for k, val in (items.items() if isinstance(items, dict) else items):
            _w_value(out, ktype, k)
            _w_value(out, vtype, val)
    elif ttype in (T_SET, T_LIST):
        etype, items = v
        out += struct.pack(">bi", etype, len(items))
        for it in items:
            _w_value(out, etype, it)
    else:
        raise ValueError(f"cannot encode thrift type {ttype}")


def _w_struct(out: bytearray, fields) -> None:
    """fields: iterable of TField (or (id, ttype, value) tuples)."""
    for f in fields:
        if not isinstance(f, TField):
            f = TField(*f)
        out += struct.pack(">bh", f.ttype, f.id)
        _w_value(out, f.ttype, f.value)
    out.append(T_STOP)


def encode_message(name: str, mtype: int, seqid: int, fields) -> bytes:
    body = bytearray()
    body += struct.pack(">I", VERSION_1 | mtype)
    raw = name.encode()
    body += struct.pack(">i", len(raw)) + raw
    body += struct.pack(">i", seqid)
    _w_struct(body, fields)
    return struct.pack(">I", len(body)) + bytes(body)


# ---- binary reader ---------------------------------------------------------

class _Reader:
    def __init__(self, data: bytes, pos: int = 0):
        self.d = data
        self.p = pos

    def take(self, n: int) -> bytes:
        if self.p + n > len(self.d):
            raise ValueError("truncated thrift payload")
        v = self.d[self.p:self.p + n]
        self.p += n
        return v

    def unpack(self, fmt: str):
        s = struct.Struct(fmt)
        return s.unpack(self.take(s.size))[0]

    def value(self, ttype: int):
        if ttype == T_BOOL:
            return bool(self.take(1)[0])
        if ttype == T_BYTE:
            return self.unpack(">b")
        if ttype == T_DOUBLE:
            return self.unpack(">d")
        if ttype == T_I16:
            return self.unpack(">h")
        if ttype == T_I32:
            return self.unpack(">i")
        if ttype == T_I64:
            return self.unpack(">q")
        if ttype == T_STRING:
            n = self.unpack(">i")
            if n < 0:
                raise ValueError("negative string length")
            return self.take(n)
        if ttype == T_STRUCT:
            return self.struct_()
        if ttype == T_MAP:
            ktype = self.unpack(">b")
            vtype = self.unpack(">b")
            n = self.unpack(">i")
            return {self.value(ktype): self.value(vtype) for _ in range(n)}
        if ttype in (T_SET, T_LIST):
            etype = self.unpack(">b")
            n = self.unpack(">i")
            return [self.value(etype) for _ in range(n)]
        raise ValueError(f"cannot decode thrift type {ttype}")

    def struct_(self) -> dict[int, Any]:
        out: dict[int, Any] = {}
        while True:
            ttype = self.take(1)[0]
            if ttype == T_STOP:
                return out
            fid = self.unpack(">h")
            out[fid] = self.value(ttype)


class ThriftMessage:
    __slots__ = ("name", "mtype", "seqid", "fields")

    def __init__(self, name: str, mtype: int, seqid: int,
                 fields: dict[int, Any]):
        self.name = name
        self.mtype = mtype
        self.seqid = seqid
        self.fields = fields


def decode_message(payload: bytes) -> ThriftMessage:
    """payload = TBinaryProtocol bytes WITHOUT the u32be frame length (the
    native parser strips it; MSG_THRIFT body is exactly this)."""
    r = _Reader(payload)
    ver = r.unpack(">I")
    if ver & 0xFFFF0000 != VERSION_1:
        raise ValueError(f"bad thrift version 0x{ver:08x}")
    mtype = ver & 0xFF
    nlen = r.unpack(">i")
    name = r.take(nlen).decode("utf-8", "replace")
    seqid = r.unpack(">i")
    fields = r.struct_()
    return ThriftMessage(name, mtype, seqid, fields)


def encode_exception(name: str, seqid: int, message: str,
                     etype: int = 6) -> bytes:
    return encode_message(name, MT_EXCEPTION, seqid, [
        TField(1, T_STRING, message), TField(2, T_I32, etype)])


# ---- client ----------------------------------------------------------------

class ThriftChannel:
    """Framed-binary thrift client with pipelined calls matched by seqid
    (reference thrift client role of policy/thrift_protocol.cpp).

        ch = ThriftChannel("127.0.0.1:9090")
        result = ch.call("add", [TField(1, T_I32, 2), TField(2, T_I32, 3)])
        # result: reply struct dict; result[0] is the conventional
        # 'success' field
    """

    def __init__(self, address: str, timeout_ms: int = 1000):
        host, _, port = address.rpartition(":")
        self.host, self.port = host or "127.0.0.1", int(port)
        self.timeout_ms = timeout_ms
        self._mu = threading.Lock()
        self._sid: Optional[int] = None
        self._seq = 0
        self._pending: dict[int, Future] = {}

    def _ensure_connected(self) -> int:
        with self._mu:
            t = Transport.instance()
            if self._sid is not None and t.alive(self._sid):
                return self._sid
            self._fail_pending_locked()
            self._sid = t.connect(self.host, self.port, self._on_message,
                                  self._on_failed)
            return self._sid

    def _fail_pending_locked(self) -> None:
        pend, self._pending = self._pending, {}
        for fut in pend.values():
            if not fut.done():
                fut.set_exception(errors.RpcError(errors.EFAILEDSOCKET,
                                                  "thrift conn lost"))

    def _on_failed(self, sid: int, err: int) -> None:
        with self._mu:
            if sid == self._sid:
                self._sid = None
            self._fail_pending_locked()

    def _on_message(self, sid: int, kind: int, meta: bytes, body) -> None:
        if kind != MSG_THRIFT:
            return
        try:
            msg = decode_message(body.to_bytes())
        except ValueError:
            return
        with self._mu:
            fut = self._pending.pop(msg.seqid, None)
        if fut is None or fut.done():
            return
        if msg.mtype == MT_EXCEPTION:
            fut.set_exception(ThriftError(
                msg.fields.get(1, b"").decode("utf-8", "replace")
                if isinstance(msg.fields.get(1), bytes) else
                str(msg.fields.get(1, "")),
                msg.fields.get(2, 0)))
        else:
            fut.set_result(msg.fields)

    def acall(self, method: str, fields=(), oneway: bool = False) -> Future:
        sid = self._ensure_connected()
        fut: Future = Future()
        with self._mu:
            self._seq += 1
            seqid = self._seq
            if not oneway:
                self._pending[seqid] = fut
        wire = encode_message(method, MT_ONEWAY if oneway else MT_CALL,
                              seqid, fields)
        if Transport.instance().write_raw(sid, wire) != 0:
            with self._mu:
                self._pending.pop(seqid, None)
            fut.set_exception(errors.RpcError(errors.EFAILEDSOCKET,
                                              "thrift write failed"))
        elif oneway:
            fut.set_result({})
        return fut

    def call(self, method: str, fields=(), timeout_ms: Optional[int] = None
             ) -> dict[int, Any]:
        fut = self.acall(method, fields)
        try:
            return fut.result((timeout_ms or self.timeout_ms) / 1e3)
        except TimeoutError:
            raise errors.RpcError(errors.ERPCTIMEDOUT,
                                  f"thrift call {method!r} timed out")

    def close(self) -> None:
        # release _mu before the native close: the failed-callback fires
        # synchronously on this thread and takes _mu (redis.py pattern)
        with self._mu:
            sid, self._sid = self._sid, None
        if sid is not None:
            Transport.instance().close(sid)


# ---- server ----------------------------------------------------------------

class ThriftService:
    """Server-side thrift method registry (the ThriftService adaptor slot of
    thrift_service.h).  Handlers take the decoded args struct dict and
    return the reply fields (a TField list, a dict {id: TField}, or a bare
    value which becomes success field 0 — T_STRING for bytes/str,
    T_I64 for int, T_DOUBLE for float, T_BOOL for bool).

        svc = ThriftService()

        @svc.method("add")
        def add(args):
            return TField(0, T_I32, args[1] + args[2])
    """

    def __init__(self):
        self._methods: dict[str, Callable] = {}

    def method(self, name: str):
        def deco(fn):
            self._methods[name] = fn
            return fn
        return deco

    def add_handler(self, name: str, fn: Callable) -> None:
        self._methods[name] = fn

    @staticmethod
    def _to_fields(result) -> list:
        if result is None:
            return []
        if isinstance(result, TField):
            return [result]
        if isinstance(result, (list, tuple)):
            return list(result)
        if isinstance(result, bool):
            return [TField(0, T_BOOL, result)]
        if isinstance(result, int):
            return [TField(0, T_I64, result)]
        if isinstance(result, float):
            return [TField(0, T_DOUBLE, result)]
        if isinstance(result, (str, bytes)):
            return [TField(0, T_STRING, result)]
        raise TypeError(f"cannot infer thrift type for {type(result)!r}")

    def handle_bytes(self, framed: bytes) -> bytes:
        try:
            msg = decode_message(framed)
        except ValueError as e:
            return encode_exception("unknown", 0, f"bad request: {e}", 7)
        fn = self._methods.get(msg.name)
        if fn is None:
            return encode_exception(msg.name, msg.seqid,
                                    f"unknown method {msg.name!r}", 1)
        try:
            result = fn(msg.fields)
        except Exception as e:
            return encode_exception(msg.name, msg.seqid,
                                    f"{type(e).__name__}: {e}", 6)
        if msg.mtype == MT_ONEWAY:
            return b""
        try:
            return encode_message(msg.name, MT_REPLY, msg.seqid,
                                  self._to_fields(result))
        except (TypeError, ValueError) as e:
            return encode_exception(msg.name, msg.seqid,
                                    f"bad reply: {e}", 6)
