"""TLS for the RPC stack — in-process termination/initiation proxies.

Reference: brpc::Socket carries OpenSSL state inline (socket.h SSL
members; ServerOptions.ssl_options, ChannelOptions has_ssl) — ciphertext
and plaintext share one fd.  This build's native core has no OpenSSL (no
C headers in the image), so TLS rides Python's ssl module in the
termination-proxy shape every production mesh already uses (envoy/
stunnel): a TLS listener decrypts and pumps plaintext over a loopback
connection into the native listener, and a client-side initiator does the
reverse.  The native hot path (parse, dispatch, wait-free writes) is
unchanged; TLS costs one local hop, which is the honest price of
userspace TLS without native bindings.

    server:  Server(...).start(...); TlsTerminator(server, cert, key)
    client:  ch = Channel(tls_channel_address(host, port, cafile=...))

tls_channel_address starts (and caches) a TlsInitiator for the upstream
and returns the local plaintext address a normal Channel can dial.
"""
from __future__ import annotations

import selectors
import socket
import ssl
import threading
from typing import Optional

from brpc_tpu.bvar import Adder

_tls_conns = Adder("rpc_tls_connections")
_tls_bytes_in = Adder("rpc_tls_bytes_in")
_tls_bytes_out = Adder("rpc_tls_bytes_out")


class _Pump(threading.Thread):
    """Bidirectional byte pump between two sockets (one per direction
    pair; blocking IO with small buffers — TLS connections are the slow
    path by construction here)."""

    def __init__(self, a: socket.socket, b: socket.socket, counter: Adder):
        super().__init__(daemon=True)
        self._a = a
        self._b = b
        self._counter = counter

    def run(self):
        try:
            while True:
                data = self._a.recv(65536)
                if not data:
                    # half-close: propagate only SHUT_WR so the opposite
                    # pump can still drain an in-flight response
                    try:
                        self._b.shutdown(socket.SHUT_WR)
                    except OSError:
                        pass
                    return
                self._counter.add(len(data))
                self._b.sendall(data)
        except OSError:
            # hard error: tear down both directions
            for s in (self._a, self._b):
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass


class TlsTerminator:
    """Server side: TLS listener that forwards plaintext to the native
    RPC listener.  All protocols multiplexed on the native port work over
    TLS unchanged (TRPC, HTTP console, redis, ...)."""

    def __init__(self, server, certfile: str, keyfile: str,
                 address: str = "0.0.0.0", port: int = 0,
                 require_client_cert: bool = False,
                 cafile: Optional[str] = None):
        if not server.port:
            # UDS-started servers have no port (bound_port=0); terminate
            # TLS in front of a TCP listener, or add UDS backend support
            # explicitly — silently dialing port 0 would drop every
            # connection
            raise ValueError(
                "TlsTerminator needs a TCP-started server (server.port is "
                "0 — unix-socket servers are not a dialable TCP backend)")
        self._server = server   # port re-read per connection: restart-safe
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(certfile, keyfile)
        if require_client_cert:
            ctx.verify_mode = ssl.CERT_REQUIRED
            if cafile:
                ctx.load_verify_locations(cafile)
        self._ctx = ctx
        self._lsock = socket.socket()
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((address, port))
        self._lsock.listen(128)
        self.port = self._lsock.getsockname()[1]
        self._stopping = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"tls-terminator-{self.port}")
        self._accept_thread.start()

    def _accept_loop(self):
        while not self._stopping.is_set():
            try:
                raw, _ = self._lsock.accept()
            except OSError:
                return
            threading.Thread(target=self._handle, args=(raw,),
                             daemon=True).start()

    def _handle(self, raw: socket.socket):
        try:
            tls = self._ctx.wrap_socket(raw, server_side=True)
        except (ssl.SSLError, OSError):
            raw.close()
            return
        try:
            plain = socket.create_connection(
                ("127.0.0.1", self._server.port), timeout=10)
        except OSError:
            tls.close()
            return
        # the connect timeout must not linger: a pumped connection idle
        # >10s would otherwise die with TimeoutError in the pump
        plain.settimeout(None)
        tls.settimeout(None)
        _tls_conns.add(1)
        _Pump(tls, plain, _tls_bytes_in).start()
        _Pump(plain, tls, _tls_bytes_out).start()

    def stop(self):
        self._stopping.set()
        try:
            self._lsock.close()
        except OSError:
            pass


class TlsInitiator:
    """Client side: local plaintext listener that dials the remote over
    TLS — a normal Channel connects to `local_port` and its bytes ride
    the encrypted upstream (ChannelOptions ssl in the reference)."""

    def __init__(self, host: str, port: int, cafile: Optional[str] = None,
                 verify: bool = True,
                 certfile: Optional[str] = None,
                 keyfile: Optional[str] = None):
        self._upstream = (host, port)
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        if cafile:
            ctx.load_verify_locations(cafile)
        if not verify:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        if certfile:
            ctx.load_cert_chain(certfile, keyfile)
        self._ctx = ctx
        self._host = host
        self._lsock = socket.socket()
        self._lsock.bind(("127.0.0.1", 0))
        self._lsock.listen(64)
        self.local_port = self._lsock.getsockname()[1]
        self._stopping = threading.Event()
        threading.Thread(target=self._accept_loop, daemon=True,
                         name=f"tls-initiator-{self.local_port}").start()

    def _accept_loop(self):
        while not self._stopping.is_set():
            try:
                plain, _ = self._lsock.accept()
            except OSError:
                return
            threading.Thread(target=self._handle, args=(plain,),
                             daemon=True).start()

    def _handle(self, plain: socket.socket):
        try:
            raw = socket.create_connection(self._upstream, timeout=10)
            tls = self._ctx.wrap_socket(raw, server_hostname=self._host)
        except (ssl.SSLError, OSError):
            plain.close()
            return
        tls.settimeout(None)     # see TlsTerminator._handle
        plain.settimeout(None)
        _tls_conns.add(1)
        _Pump(plain, tls, _tls_bytes_out).start()
        _Pump(tls, plain, _tls_bytes_in).start()

    def stop(self):
        self._stopping.set()
        try:
            self._lsock.close()
        except OSError:
            pass


_initiators: dict = {}
_initiators_mu = threading.Lock()


def tls_channel_address(host: str, port: int, cafile: Optional[str] = None,
                        verify: bool = True,
                        certfile: Optional[str] = None,
                        keyfile: Optional[str] = None) -> str:
    """Address a Channel can dial to reach host:port over TLS.  One
    initiator per upstream is cached process-wide (like the SocketMap)."""
    key = (host, port, cafile, verify, certfile, keyfile)
    with _initiators_mu:
        init = _initiators.get(key)
        if init is None:
            init = TlsInitiator(host, port, cafile=cafile, verify=verify,
                                certfile=certfile, keyfile=keyfile)
            _initiators[key] = init
        return f"127.0.0.1:{init.local_port}"


def tls_stats() -> dict:
    return {"connections": _tls_conns.get_value(),
            "bytes_in": _tls_bytes_in.get_value(),
            "bytes_out": _tls_bytes_out.get_value()}
