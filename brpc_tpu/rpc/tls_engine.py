"""In-socket TLS: a per-connection ``ssl.MemoryBIO`` engine pumped
through the native socket's transport filter (VERDICT r4 #9; reference
integrates SSL into the Socket itself, src/brpc/socket.h:276-278 +
details/ssl_helper.h — our image has no OpenSSL headers, so the record
layer runs on Python's ``ssl`` while framing/parse/dispatch stay
native).

Flow, per connection:
  inbound   fd -> native read -> MSG_FILTERED ciphertext (FIFO lane)
            -> TlsEngine.feed_ciphertext -> SSLObject.read plaintext
            -> brpc_socket_inject -> native parse -> normal dispatch
  outbound  protocol bytes -> Transport interception -> write_plain
            -> SSLObject.write -> ciphertext -> native write_raw

Unlike the stunnel-shaped proxies in rpc/tls.py (kept for compat), the
SAME socket carries TLS: no loopback hop, no second fd, and every
protocol on the port (TRPC, HTTP console, h2/gRPC, redis, ...) rides it
transparently.

Known limitation: native-packed writes that bypass the Python transport
(the usercode latency-budget ELIMIT shed and pure-native inline_run
method handlers) would emit plaintext — do not combine those features
with TLS on the same port; the Python handler path (the normal server
configuration) is fully intercepted.
"""
from __future__ import annotations

import ssl
import threading
from typing import Optional

from brpc_tpu._core import core


class TlsError(Exception):
    pass


class TlsEngine:
    """One side of a TLS connection over a filtered native socket.

    Thread-safety: ``feed_ciphertext`` runs on the socket's FIFO lane
    (serialized); ``write_plain`` may come from any caller thread — the
    RLock serializes the SSLObject, whose BIO pairs are not
    thread-safe."""

    def __init__(self, sid: int, context: ssl.SSLContext, server_side: bool,
                 server_hostname: Optional[str] = None):
        self.sid = sid
        self._in = ssl.MemoryBIO()
        self._out = ssl.MemoryBIO()
        self._obj = context.wrap_bio(self._in, self._out,
                                     server_side=server_side,
                                     server_hostname=server_hostname)
        self._mu = threading.RLock()
        self._handshaken = False
        self._failed: Optional[str] = None
        self._pending_plain: list[bytes] = []

    # ---- inbound (FIFO-lane thread) ----

    def feed_ciphertext(self, data: bytes) -> None:
        with self._mu:
            if self._failed is not None:
                return
            self._in.write(data)
            self._pump_locked()

    # ---- outbound (any thread) ----

    def write_plain(self, data: bytes) -> int:
        """Queue plaintext for the peer.  Before the handshake finishes
        the bytes are buffered and flushed the moment it does — callers
        never block on the handshake."""
        with self._mu:
            if self._failed is not None:
                return -1
            if not self._handshaken:
                self._pending_plain.append(bytes(data))
                # opportunistically advance the handshake (client hello
                # on a fresh client engine rides this path)
                self._pump_locked()
                return 0
            self._obj.write(data)
            return self._flush_out_locked()

    def start(self) -> None:
        """Kick the handshake (client side: emits ClientHello)."""
        with self._mu:
            self._pump_locked()

    # ---- internals (call with _mu held) ----

    def _pump_locked(self) -> None:
        if not self._handshaken:
            try:
                self._obj.do_handshake()
                self._handshaken = True
                for p in self._pending_plain:
                    self._obj.write(p)
                self._pending_plain.clear()
            except ssl.SSLWantReadError:
                self._flush_out_locked()
                return
            except ssl.SSLError as e:
                self._fail_locked(f"handshake failed: {e}")
                return
        # drain decrypted application data back into the native parser
        while True:
            try:
                chunk = self._obj.read(1 << 16)
            except ssl.SSLWantReadError:
                break
            except ssl.SSLZeroReturnError:
                self._orderly_eof_locked()
                return
            except ssl.SSLError as e:
                self._fail_locked(f"record layer failed: {e}")
                return
            if not chunk:
                # SSLObject.read returns b"" (rather than raising
                # ZeroReturn on this CPython) when the peer's
                # close_notify arrives
                self._orderly_eof_locked()
                return
            core.brpc_socket_inject(self.sid, chunk, len(chunk))
        self._flush_out_locked()

    def _orderly_eof_locked(self) -> None:
        """Peer sent close_notify: answer with ours (a vanilla peer's
        unwrap() blocks waiting for it), mark the engine closed so any
        concurrent write_plain returns -1 instead of touching the
        shut-down SSLObject, and fail the socket after a short grace —
        an immediate SetFailed would discard queued-but-unwritten bytes
        (including the answering close_notify) under write
        backpressure."""
        self._failed = "closed by peer (close_notify)"
        try:
            self._obj.unwrap()
        except ssl.SSLError:
            pass
        self._flush_out_locked()
        sid = self.sid
        try:
            from brpc_tpu.rpc.transport import Transport
            Transport.instance().schedule(
                0.05, lambda: core.brpc_socket_set_failed(sid, 0))
        except Exception:
            core.brpc_socket_set_failed(sid, 0)

    def _flush_out_locked(self) -> int:
        data = self._out.read()
        if data:
            return core.brpc_socket_write_raw(self.sid, data, len(data),
                                              None)
        return 0

    def _fail_locked(self, why: str) -> None:
        self._failed = why
        # EPROTO-shaped close: the peer sees a dead connection, local
        # callers see EFAILEDSOCKET via the normal failure path
        core.brpc_socket_set_failed(self.sid, 71)


def make_server_context(certfile: str, keyfile: str) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(certfile, keyfile)
    return ctx


def make_client_context(cafile: Optional[str] = None,
                        insecure: bool = False) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    if cafile:
        ctx.load_verify_locations(cafile)
    if insecure:
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
    return ctx
