"""TransportManager — Python-side hub over the native socket core.

Owns the process-lifetime ctypes callbacks (native sockets keep raw pointers
to them), routes complete messages by SocketId to the registered handler
(client connection or server), and wraps the native timer thread for
timeout/backup timers.  This is the Python face of the reference's
InputMessenger + SocketMap glue (SURVEY.md §2.3).
"""
from __future__ import annotations

import ctypes
import threading
from typing import Callable, Optional

from brpc_tpu._core import (ACCEPTED_CB, FAILED_CB, H2_EVENT_CB, IOBuf,
                            MESSAGE_CB,
                            MSG_FILTERED, MSG_H2, MSG_HTTP, MSG_MEMCACHE,
                            MSG_MONGO, MSG_NSHEAD, MSG_RAW, MSG_REDIS,
                            MSG_THRIFT, MSG_TRPC, REQUEST_CB, RESPONSE_CB,
                            TASK_CB, core, core_init)
from brpc_tpu._core import _fastrpc
from brpc_tpu import fault


def _apply_send_fault(sid: int, payload):
    """ONE interpreter for every transport.send site (call only behind
    ``fault.ENABLED``).  Returns (rc, payload): a non-None rc
    short-circuits the write; otherwise the caller writes `payload`,
    which a CORRUPT fault mangles in place.  Each site passes the bytes
    whose corruption is meaningful there — the meta for framed writes
    (peer-side decode discards the frame), the raw buffer or the body
    for the others — so a counted injection is never a no-op."""
    f = fault.hit("transport.send", sid=sid)
    if f is None:
        return None, payload
    if f.kind == fault.CORRUPT:
        return None, fault.mangle(bytes(payload)) if payload else payload
    if f.kind == fault.OVERCROWD:
        return -2, payload
    if f.kind in (fault.RESET, fault.PARTIAL):
        if f.kind == fault.PARTIAL:
            # a torn prefix reaches the peer's parser before the close —
            # the classic half-written frame of a mid-write process death
            torn = b"TRPC\x00\x00\x00\x08"
            try:
                core.brpc_socket_write_raw(sid, torn, len(torn), None)
            except Exception:
                pass
        core.brpc_socket_set_failed(sid, 104)   # ECONNRESET
        return -1, payload
    return f.rc, payload   # ERROR: plain write failure


class Transport:
    _instance: Optional["Transport"] = None
    _instance_lock = threading.Lock()

    @classmethod
    def instance(cls) -> "Transport":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def __init__(self):
        core_init()
        self._lock = threading.Lock()
        # sid -> (on_message(sid, kind, meta_bytes, body: IOBuf),
        #         on_failed(sid, err))
        self._handlers: dict[int, tuple[Callable, Callable]] = {}
        # sid -> fast-path handlers (natively pre-parsed metas)
        self._request_handlers: dict[int, Callable] = {}
        self._response_handlers: dict[int, Callable] = {}
        # sid -> NativeH2Bridge (listener entries inherited by accepted
        # connections, exactly like _handlers)
        self._h2_bridges: dict[int, object] = {}
        self._request_cb_installed = False
        self._h2_cb_installed = False
        self._timer_lock = threading.Lock()
        self._timer_cbs: dict[int, Callable[[], None]] = {}
        self._timer_token = 1
        # in-socket TLS (rpc/tls_engine.py): sid -> TlsEngine, and TLS
        # listeners whose accepted connections auto-wrap
        self._tls: dict[int, object] = {}
        self._tls_listener_ctx: dict[int, object] = {}

        # Process-lifetime trampolines (pinned as attributes).
        @MESSAGE_CB
        def _on_message(sid, kind, meta, meta_len, body, user):
            buf = IOBuf(handle=body)  # takes ownership, freed at GC
            if kind == MSG_FILTERED:
                # in-socket TLS: ciphertext for this connection's engine;
                # decrypted bytes re-enter the native parser via inject
                eng = self._tls.get(sid)
                if eng is not None:
                    eng.feed_ciphertext(buf.to_bytes())
                return
            m = ctypes.string_at(meta, meta_len) if meta_len else b""
            if fault.ENABLED:
                f = fault.hit("transport.recv", sid=sid, kind=kind)
                if f is not None:
                    if f.kind == fault.DROP:
                        return          # delivered by TCP, lost above it
                    if f.kind == fault.CORRUPT:
                        # mangled meta fails RpcMeta.decode downstream —
                        # the frame is discarded exactly like line noise
                        m = fault.mangle(m)
            h = self._handlers.get(sid)
            if h is not None:
                try:
                    h[0](sid, kind, m, buf)
                except Exception:  # pragma: no cover - handler bug guard
                    import traceback
                    traceback.print_exc()

        @FAILED_CB
        def _on_failed(sid, err, user):
            with self._lock:
                h = self._handlers.pop(sid, None)
                self._request_handlers.pop(sid, None)
                self._response_handlers.pop(sid, None)
                bridge = self._h2_bridges.pop(sid, None)
                self._tls.pop(sid, None)
                self._tls_listener_ctx.pop(sid, None)
            if bridge is not None:
                try:
                    bridge.on_connection_failed(sid)
                except Exception:  # pragma: no cover
                    import traceback
                    traceback.print_exc()
            if h is not None and h[1] is not None:
                try:
                    h[1](sid, err)
                except Exception:  # pragma: no cover
                    import traceback
                    traceback.print_exc()

        @ACCEPTED_CB
        def _on_accepted(listener, conn, user):
            h = self._handlers.get(listener)
            if h is not None:
                # Accepted connections inherit the listener's handlers.
                with self._lock:
                    self._handlers[conn] = h
            rh = self._request_handlers.get(listener)
            if rh is not None:
                with self._lock:
                    self._request_handlers[conn] = rh
            br = self._h2_bridges.get(listener)
            if br is not None:
                with self._lock:
                    self._h2_bridges[conn] = br
            ctx = self._tls_listener_ctx.get(listener)
            if ctx is not None:
                # TLS listener: wrap the accepted connection BEFORE any
                # byte parses (accepted sockets are defer-registered, so
                # the filter flag is in place when the fd is armed)
                self.enable_tls(conn, ctx, server_side=True)

        # fast-path dispatchers (_fastrpc C extension: natively pre-parsed
        # metas arrive as flat args; the body is an IOBuf-backed READ-ONLY
        # memoryview — zero-copy, pins the blocks while referenced)
        def _on_request(sid, cid, attempt, service, method_, compress,
                        timeout_ms, content_type, attachment_size, body):
            h = self._request_handlers.get(sid)
            if h is None:
                # No per-socket handler (listener torn down mid-flight):
                # reply EINTERNAL rather than leaving the caller to hang
                # until its deadline.
                _fastrpc.send_response(sid, cid, attempt, 2001,
                                       "no request handler", "", b"")
                return
            try:
                h(sid, cid, attempt, service, method_, compress,
                  timeout_ms, content_type, attachment_size, body)
            except Exception:  # pragma: no cover - handler bug guard
                import traceback
                traceback.print_exc()
                try:
                    _fastrpc.send_response(sid, cid, attempt, 2001,
                                           "python handler raised", "", b"")
                except Exception:
                    pass

        def _on_response(sid, cid, attempt, error_code, error_text, compress,
                         content_type, attachment_size, body):
            h = self._response_handlers.get(sid)
            if h is not None:
                try:
                    h(sid, cid, attempt, error_code, error_text, compress,
                      content_type, attachment_size, body)
                except Exception:  # pragma: no cover
                    import traceback
                    traceback.print_exc()

        _fastrpc.set_response_handler(_on_response)

        @TASK_CB
        def _on_timer(arg):
            token = arg or 0
            with self._timer_lock:
                fn = self._timer_cbs.pop(token, None)
            if fn is not None:
                try:
                    fn()
                except Exception:  # pragma: no cover
                    import traceback
                    traceback.print_exc()

        self._cb_message = _on_message
        self._cb_failed = _on_failed
        self._cb_accepted = _on_accepted
        self._cb_timer = _on_timer
        self._cb_request = _on_request
        self._cb_response = _on_response

    # ---- sockets ----

    def listen(self, addr: str, port: int, on_message, on_failed=None,
               native_echo: bool = False) -> tuple[int, int]:
        sid = ctypes.c_uint64()
        bound = ctypes.c_int()
        rc = core.brpc_listen(addr.encode(), port, self._cb_message,
                              self._cb_failed, self._cb_accepted, None,
                              1 if native_echo else 0, ctypes.byref(sid),
                              ctypes.byref(bound))
        if rc != 0:
            raise OSError(f"listen on {addr}:{port} failed")
        with self._lock:
            self._handlers[sid.value] = (on_message, on_failed)
        return sid.value, bound.value

    def connect(self, host: str, port: int, on_message, on_failed=None) -> int:
        if fault.ENABLED and fault.hit("transport.connect", host=host,
                                       port=port) is not None:
            raise ConnectionError(
                f"injected connect refusal to {host}:{port}")
        sid = ctypes.c_uint64()
        rc = core.brpc_connect(host.encode(), port, self._cb_message,
                               self._cb_failed, None, ctypes.byref(sid))
        if rc != 0:
            raise ConnectionError(f"connect to {host}:{port} failed")
        with self._lock:
            self._handlers[sid.value] = (on_message, on_failed)
        return sid.value

    def listen_rpc(self, addr: str, port: int, on_message, on_failed=None,
                   on_request=None) -> tuple[int, int]:
        """Listen with the native unary fast path enabled: TRPC requests
        whose meta parses cleanly and whose method is registered
        (register_python_method) arrive pre-parsed at on_request(sid, hdr,
        body); everything else falls back to on_message."""
        if on_request is not None and not self._request_cb_installed:
            _fastrpc.set_request_handler(self._cb_request)
            self._request_cb_installed = True
        sid = ctypes.c_uint64()
        bound = ctypes.c_int()
        rc = core.brpc_listen_rpc(addr.encode(), port, self._cb_message,
                                  self._cb_failed, self._cb_accepted, None,
                                  ctypes.byref(sid), ctypes.byref(bound))
        if rc != 0:
            raise OSError(f"listen on {addr}:{port} failed")
        with self._lock:
            self._handlers[sid.value] = (on_message, on_failed)
            if on_request is not None:
                self._request_handlers[sid.value] = on_request
        return sid.value, bound.value

    def listen_rpc_h2(self, addr: str, port: int, on_message, bridge,
                      on_failed=None, on_request=None) -> tuple[int, int]:
        """listen_rpc + the NATIVE h2/gRPC data plane: accepted
        connections run framing/HPACK/flow control in C++ (net/h2.cc)
        and surface per-message events to `bridge`
        (rpc/h2_native.NativeH2Bridge)."""
        if on_request is not None and not self._request_cb_installed:
            _fastrpc.set_request_handler(self._cb_request)
            self._request_cb_installed = True
        self._ensure_h2_event_cb()
        sid = ctypes.c_uint64()
        bound = ctypes.c_int()
        rc = core.brpc_listen_rpc_h2(addr.encode(), port, self._cb_message,
                                     self._cb_failed, self._cb_accepted,
                                     None, ctypes.byref(sid),
                                     ctypes.byref(bound))
        if rc != 0:
            raise OSError(f"listen on {addr}:{port} failed")
        with self._lock:
            self._handlers[sid.value] = (on_message, on_failed)
            self._h2_bridges[sid.value] = bridge
            if on_request is not None:
                self._request_handlers[sid.value] = on_request
        return sid.value, bound.value

    def _ensure_h2_event_cb(self) -> None:
        if self._h2_cb_installed:
            return
        self._h2_cb_installed = True

        @H2_EVENT_CB
        def _on_h2_event(sid, stream_id, kind, service, service_len,
                         method, method_len, headers, headers_len,
                         body_iobuf, mflags, user):
            svc = ctypes.string_at(service, service_len).decode(
                "utf-8", "replace") if service_len else ""
            meth = ctypes.string_at(method, method_len).decode(
                "utf-8", "replace") if method_len else ""
            hdrs = ctypes.string_at(headers, headers_len) if headers_len \
                else b""
            body = None
            if body_iobuf:
                buf = IOBuf(handle=body_iobuf)  # owns; freed at GC
                body = buf.to_bytes()
            bridge = self._h2_bridges.get(sid)
            if bridge is None:
                return
            try:
                bridge.on_event(sid, stream_id, kind, svc, meth, hdrs,
                                body, mflags)
            except Exception:  # pragma: no cover - bridge bug guard
                import traceback
                traceback.print_exc()

        self._cb_h2_event = _on_h2_event      # pin for process lifetime
        core.brpc_h2_set_event_cb(_on_h2_event, None)

    def connect_rpc(self, host: str, port: int, on_message, on_failed=None,
                    on_response=None) -> int:
        """Connect with the pre-parsed response fast path (the C response
        trampoline from _fastrpc — zero ctypes on the per-response path)."""
        if fault.ENABLED and fault.hit("transport.connect", host=host,
                                       port=port) is not None:
            raise ConnectionError(
                f"injected connect refusal to {host}:{port}")
        sid = ctypes.c_uint64()
        rc = core.brpc_connect_rpc(
            host.encode(), port, self._cb_message, self._cb_failed,
            ctypes.cast(_fastrpc.response_cb_ptr(), RESPONSE_CB), None,
            ctypes.byref(sid))
        if rc != 0:
            raise ConnectionError(f"connect to {host}:{port} failed")
        with self._lock:
            self._handlers[sid.value] = (on_message, on_failed)
            if on_response is not None:
                self._response_handlers[sid.value] = on_response
        return sid.value

    # ---- in-socket TLS (rpc/tls_engine.py) ----

    def enable_tls(self, sid: int, context, server_side: bool,
                   server_hostname: str | None = None) -> None:
        """Switch `sid` into TLS mode: the native socket delivers raw
        ciphertext to a per-connection MemoryBIO engine and plaintext is
        re-injected into its parser; all outbound writes through this
        transport are encrypted.  Call before any traffic (right after
        connect, or from the accept hook)."""
        from brpc_tpu.rpc.tls_engine import TlsEngine
        eng = TlsEngine(sid, context, server_side, server_hostname)
        with self._lock:
            self._tls[sid] = eng
        core.brpc_socket_set_filter(sid, 1)
        if not server_side:
            eng.start()   # emit ClientHello

    def enable_tls_listener(self, listener_sid: int, context) -> None:
        """Every connection accepted by `listener_sid` is TLS-wrapped
        (server side) before its first byte parses."""
        with self._lock:
            self._tls_listener_ctx[listener_sid] = context

    def tls_engine(self, sid: int):
        return self._tls.get(sid)

    @staticmethod
    def _pack_trpc(meta: bytes, body: bytes) -> bytes:
        import struct
        return (b"TRPC" + struct.pack(">I", len(meta))
                + struct.pack(">Q", len(body)) + meta + body)

    @staticmethod
    def register_python_method(service: str, method: str) -> None:
        core.brpc_register_python_method(service.encode(), method.encode())

    @staticmethod
    def unregister_method(service: str, method: str) -> None:
        core.brpc_unregister_method(service.encode(), method.encode())

    @staticmethod
    def send_request(sid: int, cid: int, attempt: int, service: str,
                     method: str, timeout_ms: int, compress: int,
                     content_type: str, body: bytes) -> int:
        """Pack + write a TRPC request frame natively (no Python meta
        encode, no ctypes marshalling).  TLS connections pack in Python
        and ride the engine instead (the native writer would emit
        plaintext)."""
        if fault.ENABLED:
            rc, body = _apply_send_fault(sid, body)
            if rc is not None:
                return rc
        inst = Transport._instance
        eng = inst._tls.get(sid) if inst is not None else None
        if eng is not None:
            from brpc_tpu.rpc import meta as M
            m = M.RpcMeta(msg_type=M.MSG_REQUEST, correlation_id=cid,
                          attempt=attempt, service=service, method=method,
                          timeout_ms=timeout_ms or 0, compress_type=compress,
                          content_type=content_type or "")
            return eng.write_plain(
                Transport._pack_trpc(m.encode(), bytes(body)))
        return _fastrpc.send_request(sid, cid, attempt, service, method,
                                     timeout_ms or 0, compress, content_type,
                                     body)

    @staticmethod
    def send_response(sid: int, cid: int, attempt: int, error_code: int,
                      error_text: str, content_type: str,
                      body: bytes) -> int:
        if fault.ENABLED:
            rc, body = _apply_send_fault(sid, body)
            if rc is not None:
                return rc
        inst = Transport._instance
        eng = inst._tls.get(sid) if inst is not None else None
        if eng is not None:
            from brpc_tpu.rpc import meta as M
            m = M.RpcMeta(msg_type=M.MSG_RESPONSE, correlation_id=cid,
                          attempt=attempt, error_code=error_code,
                          error_text=error_text or "",
                          content_type=content_type or "")
            return eng.write_plain(
                Transport._pack_trpc(m.encode(), bytes(body)))
        return _fastrpc.send_response(sid, cid, attempt, error_code,
                                      error_text or "", content_type or "",
                                      body)

    def write_frame(self, sid: int, meta: bytes, body: bytes = b"",
                    body_iobuf: IOBuf | None = None) -> int:
        if fault.ENABLED:
            # CORRUPT mangles the META: the frame arrives, parses as
            # TRPC, fails decode at the peer and is discarded —
            # in-flight corruption the framing cannot catch
            rc, meta = _apply_send_fault(sid, meta)
            if rc is not None:
                return rc
        eng = self._tls.get(sid)
        if eng is not None:
            full = bytes(body)
            if body_iobuf is not None:
                full += body_iobuf.to_bytes()
            return eng.write_plain(self._pack_trpc(bytes(meta), full))
        return core.brpc_socket_write_frame(
            sid, meta, len(meta), body, len(body),
            body_iobuf.handle if body_iobuf is not None else None)

    def write_frames(self, sid: int, frames: list[tuple[bytes, bytes]]
                     ) -> int:
        """Write a run of (meta, body) frames as ONE socket write — one
        ctypes crossing and one write-stack push instead of N (the h2
        frame-coalescing story at the TRPC layer; the parser side
        already cuts multiple frames per buffer).  One rc for the whole
        run: ordering is preserved by the single write, and a failure
        means none/all-prefix delivery exactly like N sequential writes
        on a dead socket.  For SMALL frames: the coalesced payload is
        checked against the per-write EOVERCROWDED bound as one unit and
        is materialized contiguously — big bodies should go per-frame
        (the stream sender coalesces ticket frames only)."""
        payload = b"".join(self._pack_trpc(bytes(m), bytes(b))
                           for m, b in frames)
        return self.write_raw(sid, payload)

    def write_raw(self, sid: int, data: bytes) -> int:
        if fault.ENABLED:
            rc, data = _apply_send_fault(sid, data)
            if rc is not None:
                return rc
        eng = self._tls.get(sid)
        if eng is not None:
            return eng.write_plain(bytes(data))
        return core.brpc_socket_write_raw(sid, data, len(data), None)

    def set_protocol(self, sid: int, kind: int) -> None:
        """Pre-select the wire protocol a connection's inbound bytes use
        (h2 / mongo / raw streaming clients whose first inbound bytes are
        ambiguous)."""
        core.brpc_socket_set_protocol(sid, kind)

    def close(self, sid: int, err: int = 0) -> None:
        core.brpc_socket_set_failed(sid, err)

    def alive(self, sid: int) -> bool:
        return bool(core.brpc_socket_alive(sid))

    def socket_stats(self, sid: int) -> dict | None:
        nread = ctypes.c_int64()
        nwritten = ctypes.c_int64()
        nmsg = ctypes.c_int64()
        ip = ctypes.create_string_buffer(48)
        port = ctypes.c_int()
        rc = core.brpc_socket_stats(sid, ctypes.byref(nread),
                                    ctypes.byref(nwritten), ctypes.byref(nmsg),
                                    ip, 48, ctypes.byref(port))
        if rc != 0:
            return None
        return {"bytes_read": nread.value, "bytes_written": nwritten.value,
                "messages_read": nmsg.value,
                "remote": f"{ip.value.decode()}:{port.value}"}

    # ---- timers (native TimerThread) ----

    def schedule(self, delay_s: float, fn: Callable[[], None]) -> tuple[int, int]:
        """Returns (native_timer_id, token) for cancel()."""
        with self._timer_lock:
            token = self._timer_token
            self._timer_token += 1
            self._timer_cbs[token] = fn
        tid = core.brpc_timer_add(self._cb_timer, ctypes.c_void_p(token),
                                  int(delay_s * 1e6))
        return tid, token

    def cancel(self, timer: tuple[int, int]) -> bool:
        tid, token = timer
        ok = core.brpc_timer_cancel(tid) == 0
        with self._timer_lock:
            self._timer_cbs.pop(token, None)
        return ok
