"""rpcz — per-RPC trace spans (reference src/brpc/span.h; SURVEY.md §5.1).

Span objects record the per-RPC timeline (recv/process/send timestamps,
sizes, error).  Server-side spans are installed in thread-local storage for
the duration of the handler, so nested client calls made inside it pick up
trace_id/parent_span automatically — the reference propagates the same way
through bthread-local storage (task_meta.h:44).  Collection rides the
shared bvar Collector (brpc_tpu/bvar/collector.py, reference
bvar/collector.{h,cpp}): submission is a speed-limited handoff; the
bounded recent-span store is filled on the collector thread.
"""
from __future__ import annotations

import itertools
import contextvars
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field

# The current span is a CONTEXT variable, not a thread-local: user code
# that hops executors/threads via butil.fiber_local.wrap()/spawn() (the
# bthread_key analog) carries its span with it — fiber-local span
# propagation, bthread/key.cpp:49 + the rpcz parent-span contract.
_current_span: contextvars.ContextVar = contextvars.ContextVar(
    "rpcz_span", default=None)
_span_counter = itertools.count(1)

_COLLECT_MAX = 2048
_collected: deque = deque(maxlen=_COLLECT_MAX)
_collect_lock = threading.Lock()
# Off by default, like the reference's FLAGS_enable_rpcz: span objects are
# only materialized when tracing is on; the hot path otherwise touches a
# shared null span (absorbs writes, reads as zeros).  Enable via
# set_enabled(True) or the reloadable `rpcz_enabled` flag (/flags).
_enabled = False
_sample_rate = 1.0   # 1.0 = keep all (rate-limit knob for hot servers)


def set_enabled(on: bool, sample_rate: float = 1.0) -> None:
    global _enabled, _sample_rate
    _enabled = on
    _sample_rate = sample_rate


@dataclass
class Span:
    trace_id: int = 0
    span_id: int = 0
    parent_span_id: int = 0
    service: str = ""
    method: str = ""
    remote_side: str = ""
    start_us: int = 0
    end_us: int = 0
    request_size: int = 0
    response_size: int = 0
    error_code: int = 0
    kind: str = "server"        # server | client
    annotations: list = field(default_factory=list)

    @property
    def latency_us(self) -> int:
        return max(0, self.end_us - self.start_us)

    def annotate(self, msg: str) -> None:
        self.annotations.append((int(time.time() * 1e6), msg))


class _NullSpan:
    """Stand-in when rpcz is off: absorbs attribute writes, reads as
    zeros/empties.  One shared instance; never collected."""
    __slots__ = ()
    trace_id = 0
    span_id = 0
    parent_span_id = 0
    start_us = 0
    end_us = 0
    request_size = 0
    response_size = 0
    error_code = 0
    latency_us = 0
    service = ""
    method = ""
    remote_side = ""
    kind = ""
    annotations = ()

    def __setattr__(self, k, v):
        pass

    def annotate(self, msg):
        pass


NULL_SPAN = _NullSpan()


def now_us() -> int:
    return int(time.time() * 1e6)


def new_span(kind: str, service: str = "", method: str = "",
             trace_id: int = 0, parent_span_id: int = 0) -> Span:
    if not _enabled:
        return NULL_SPAN
    s = Span(kind=kind, service=service, method=method,
             trace_id=trace_id or random.getrandbits(63),
             span_id=next(_span_counter),
             parent_span_id=parent_span_id, start_us=now_us())
    return s


def set_current_span(span: Span | None) -> None:
    _current_span.set(span)


def get_current_span() -> Span | None:
    return _current_span.get()


def current_trace() -> tuple[int, int]:
    """(trace_id, parent_span_id) to stamp on an outgoing request: inherits
    the server span when calling inside a handler (cascaded RPC)."""
    s = get_current_span()
    if s is None or not s.trace_id:
        return 0, 0
    return s.trace_id, s.span_id


class _SpanSample:
    """Collected wrapper: moves the store append (and any future
    indexing/serialization) off the RPC thread."""

    __slots__ = ("span",)

    def __init__(self, span: Span):
        self.span = span

    def dump_and_destroy(self) -> None:
        with _collect_lock:
            _collected.append(self.span)


def submit(span: Span) -> None:
    if not _enabled or span is NULL_SPAN:
        return
    if _sample_rate < 1.0 and random.random() > _sample_rate:
        return
    span.end_us = span.end_us or now_us()
    from brpc_tpu.bvar.collector import Collector, get_or_create_limit
    Collector.instance().submit(_SpanSample(span),
                                get_or_create_limit("rpcz", 2000),
                                family="rpcz")


def recent_spans(limit: int = 100, trace_id: int | None = None) -> list[Span]:
    # observe our own prior submissions; flushing ONLY the rpcz family
    # keeps this (console) thread away from other consumers' IO
    from brpc_tpu.bvar.collector import Collector
    Collector.instance().flush(family="rpcz")
    with _collect_lock:
        spans = list(_collected)
    if trace_id is not None:
        spans = [s for s in spans if s.trace_id == trace_id]
    return spans[-limit:]


def traceprintf(msg: str) -> None:
    """TRACEPRINTF analog: annotate the current span."""
    s = get_current_span()
    if s is not None:
        s.annotate(msg)
