"""rpcz — per-RPC trace spans (reference src/brpc/span.h; SURVEY.md §5.1).

Span objects record the per-RPC timeline (recv/process/send timestamps,
sizes, error).  Server-side spans are installed in thread-local storage for
the duration of the handler, so nested client calls made inside it pick up
trace_id/parent_span automatically — the reference propagates the same way
through bthread-local storage (task_meta.h:44).  Collection rides the
shared bvar Collector (brpc_tpu/bvar/collector.py, reference
bvar/collector.{h,cpp}): submission is a speed-limited handoff; the
bounded recent-span store is filled on the collector thread.
"""
from __future__ import annotations

import itertools
import contextvars
import os
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field

# The current span is a CONTEXT variable, not a thread-local: user code
# that hops executors/threads via butil.fiber_local.wrap()/spawn() (the
# bthread_key analog) carries its span with it — fiber-local span
# propagation, bthread/key.cpp:49 + the rpcz parent-span contract.
_current_span: contextvars.ContextVar = contextvars.ContextVar(
    "rpcz_span", default=None)
# pid-salted span ids (ISSUE 20): the fleet telemetry plane merges
# spans COLLECTED in several processes into one tree, so span ids must
# not collide across processes the way bare count(1) streams do.  The
# low 16 pid bits in bits 40..55 keep the id inside the uint64 the
# wire TLV carries while leaving 2^40 spans per process before overlap.
_span_counter = itertools.count(((os.getpid() & 0xFFFF) << 40) | 1)

_COLLECT_MAX = 2048
_collected: deque = deque(maxlen=_COLLECT_MAX)
# monotone collection cursor (ISSUE 20): every span landing in
# _collected gets the next seq, so a fleet collector can pull "finished
# spans since my last pull" incrementally without re-shipping the ring
_collect_seq = 0
# NAMED hot lock (ISSUE 6): every submitted span's collector handoff
# lands here — ledger row "rpcz.collect" on /hotspots/locks
from brpc_tpu.butil.lockprof import InstrumentedLock  # noqa: E402

_collect_lock = InstrumentedLock("rpcz.collect")
# Off by default, like the reference's FLAGS_enable_rpcz: span objects are
# only materialized when tracing is on; the hot path otherwise touches a
# shared null span (absorbs writes, reads as zeros).  Enable via
# set_enabled(True) or the reloadable `rpcz_enabled` flag (/flags).
_enabled = False
_sample_rate = 1.0   # 1.0 = keep all (rate-limit knob for hot servers)


def set_enabled(on: bool, sample_rate: float = 1.0) -> None:
    global _enabled, _sample_rate
    _enabled = on
    _sample_rate = sample_rate


@dataclass
class Span:
    trace_id: int = 0
    span_id: int = 0
    parent_span_id: int = 0
    service: str = ""
    method: str = ""
    remote_side: str = ""
    start_us: int = 0
    end_us: int = 0
    request_size: int = 0
    response_size: int = 0
    error_code: int = 0
    kind: str = "server"   # server | client | batch | prefill | decode |
    #                        generation | device (serving/DCN stage spans)
    annotations: list = field(default_factory=list)
    # head-sampling decision, made ONCE at the trace root and inherited
    # by every child (per-TRACE sampling: a kept trace has no holes)
    sampled: bool = True
    # crash-recovery link: the span_id of the pre-crash attempt this
    # span resumes (supervisor re-admission) — 0 when not a resumption
    recovered_from: int = 0
    # cross-host migration link (ISSUE 7), mirroring recovered_from:
    # the SOURCE process's migrate span whose pages this span spliced
    # in — 0 when this span is not a migration destination
    migrated_from: int = 0
    # collection cursor (ISSUE 20): position in THIS process's
    # recent-span store, assigned when the span lands there.  Purely
    # local bookkeeping for incremental _telemetry pulls — never
    # meaningful across processes and never persisted.
    seq: int = 0

    @property
    def latency_us(self) -> int:
        return max(0, self.end_us - self.start_us)

    def annotate(self, msg: str) -> None:
        self.annotations.append((int(time.time() * 1e6), msg))


class _NullSpan:
    """Stand-in when rpcz is off: absorbs attribute writes, reads as
    zeros/empties.  One shared instance; never collected."""
    __slots__ = ()
    trace_id = 0
    span_id = 0
    parent_span_id = 0
    start_us = 0
    end_us = 0
    request_size = 0
    response_size = 0
    error_code = 0
    latency_us = 0
    service = ""
    method = ""
    remote_side = ""
    kind = ""
    annotations = ()
    sampled = True
    recovered_from = 0
    migrated_from = 0
    seq = 0

    def __setattr__(self, k, v):
        pass

    def annotate(self, msg):
        pass


NULL_SPAN = _NullSpan()


def now_us() -> int:
    return int(time.time() * 1e6)


def enabled() -> bool:
    return _enabled


def sample_rate() -> float:
    return _sample_rate


def new_span(kind: str, service: str = "", method: str = "",
             trace_id: int = 0, parent_span_id: int = 0,
             sampled: bool | None = None) -> Span:
    """Create a span.  Head sampling is PER-TRACE: a fresh root (no
    trace_id) rolls the sample-rate die exactly once; a span joining an
    existing trace inherits the root's decision — either from the
    explicit ``sampled`` argument (wire propagation: the
    FLAG_TRACE_SAMPLED meta bit, the DCN envelope) or from the current
    span when it belongs to the same trace.  A kept trace therefore
    arrives whole; a dropped one leaves nothing, never holes."""
    if not _enabled:
        return NULL_SPAN
    if sampled is None:
        if trace_id:
            cur = _current_span.get()
            sampled = cur.sampled if (cur is not None
                                      and cur.trace_id == trace_id) else True
        else:
            sampled = _sample_rate >= 1.0 or random.random() <= _sample_rate
    s = Span(kind=kind, service=service, method=method,
             trace_id=trace_id or random.getrandbits(63),
             span_id=next(_span_counter),
             parent_span_id=parent_span_id, start_us=now_us(),
             sampled=bool(sampled))
    return s


def child_span(kind: str, service: str = "", method: str = "") -> Span:
    """A span under the CURRENT span (trace id, parentage and sampling
    inherited); a fresh root when no span is current.  The serving
    layers use this to hang stage spans off the RPC ingress span."""
    if not _enabled:
        return NULL_SPAN
    tid, psid, smp = current_trace_ctx()
    return new_span(kind, service, method, trace_id=tid,
                    parent_span_id=psid, sampled=smp if tid else None)


def set_current_span(span: Span | None) -> None:
    _current_span.set(span)


def get_current_span() -> Span | None:
    return _current_span.get()


def current_trace() -> tuple[int, int]:
    """(trace_id, parent_span_id) to stamp on an outgoing request: inherits
    the server span when calling inside a handler (cascaded RPC)."""
    s = get_current_span()
    if s is None or not s.trace_id:
        return 0, 0
    return s.trace_id, s.span_id


def current_trace_ctx() -> tuple[int, int, bool]:
    """(trace_id, parent_span_id, sampled) — current_trace plus the
    root's head-sampling decision, for callers that carry trace context
    across threads (the batcher queue, the decode slot pool, DCN call
    metadata) where the contextvar does not follow."""
    s = get_current_span()
    if s is None or not s.trace_id:
        return 0, 0, True
    return s.trace_id, s.span_id, s.sampled


# ---- on-disk SpanDB (reference span.h:227-230 keeps rpcz spans in an
# on-disk database so traces survive the in-memory window/restarts; ours
# is recordio-framed json with size rotation, written on the COLLECTOR
# thread so the RPC path never touches disk) ----
_db_lock = threading.Lock()
_db_dir: str | None = None
_db_writer = None
_db_file = None
_db_bytes = 0
_DB_ROTATE_BYTES = 16 << 20
_DB_KEEP_FILES = 4


def set_database_dir(path: str | None) -> None:
    """Enable (or disable with None) span persistence under `path`."""
    global _db_dir, _db_writer, _db_file, _db_bytes
    import os
    with _db_lock:
        if _db_file is not None:
            try:
                _db_file.close()
            except OSError:
                pass
        _db_writer = _db_file = None
        _db_bytes = 0
        _db_dir = path or None
        if _db_dir:
            os.makedirs(_db_dir, exist_ok=True)


def _db_append_locked(span: Span) -> None:
    import json
    import os

    from brpc_tpu.butil.recordio import RecordWriter
    global _db_writer, _db_file, _db_bytes
    if _db_writer is None or _db_bytes >= _DB_ROTATE_BYTES:
        if _db_file is not None:
            try:
                _db_file.close()
            except OSError:
                pass
        # prune BEFORE creating the new segment (covers restart into a
        # dir full of old segments too): keep the newest KEEP-1 so the
        # steady state is KEEP files including the one about to open
        segs = sorted(f for f in os.listdir(_db_dir)
                      if f.startswith("spans-"))
        for old in segs[:-(_DB_KEEP_FILES - 1)] if _DB_KEEP_FILES > 1 \
                else segs:
            try:
                os.unlink(os.path.join(_db_dir, old))
            except OSError:
                pass
        name = os.path.join(_db_dir, f"spans-{now_us()}.rio")
        _db_file = open(name, "ab")
        _db_writer = RecordWriter(_db_file)
        _db_bytes = 0
    rec = json.dumps(span_to_dict(span)).encode()
    _db_writer.write(rec)
    # no per-span flush: a write(2) per span would defeat buffering; the
    # reader flushes the live writer before scanning, and RecordReader
    # resyncs past any torn tail after a crash
    _db_bytes += len(rec) + 20


def load_disk_spans(limit: int = 200,
                    trace_id: int | None = None) -> list[Span]:
    """Read persisted spans back (newest segments last; resyncs past
    torn tails via RecordReader)."""
    import json
    import os

    from brpc_tpu.butil.recordio import RecordReader
    with _db_lock:
        d = _db_dir
        if _db_writer is not None:
            try:
                _db_writer.flush()   # make the live segment readable
            except OSError:
                pass
    if not d or not os.path.isdir(d):
        return []
    # newest segments first, stop as soon as `limit` spans are found —
    # older 16MB segments are never parsed for the common recent-N query
    out: list[Span] = []
    for name in sorted((f for f in os.listdir(d)
                        if f.startswith("spans-")), reverse=True):
        seg: list[Span] = []
        try:
            with open(os.path.join(d, name), "rb") as f:
                for _meta, body in RecordReader(f):
                    try:
                        rec = json.loads(body.decode())
                    except ValueError:
                        continue
                    if trace_id is not None and \
                            rec.get("trace_id") != trace_id:
                        continue
                    ann = [tuple(a) for a in rec.pop("annotations", [])]
                    seg.append(Span(annotations=ann, **rec))
        except OSError:
            continue
        out = seg + out
        if len(out) >= limit:
            break
    return out[-limit:]


class _SpanSample:
    """Collected wrapper: moves the store append (and on-disk SpanDB
    persistence) off the RPC thread — both run on the collector."""

    __slots__ = ("span",)

    def __init__(self, span: Span):
        self.span = span

    def dump_and_destroy(self) -> None:
        global _collect_seq
        with _collect_lock:
            _collect_seq += 1
            self.span.seq = _collect_seq
            _collected.append(self.span)
        with _db_lock:
            if _db_dir is not None:
                try:
                    _db_append_locked(self.span)
                except OSError:
                    pass  # disk trouble must never break collection


# ---- native span queue (ISSUE 9): the submit hot path is ONE
# lock-free push of the span object onto a native MPSC stack
# (_fastrpc.spanq_push); the rate-limit grab, recent-store append and
# SpanDB IO all run on the drainer thread, so tracing leaves the token
# path entirely.  The Collector path below remains the fallback when
# the native extension is unavailable or the flag is off. ----
_spanq_mu = threading.Lock()
_spanq_thread: threading.Thread | None = None
# SAFETY-NET park bound only (ISSUE 10): the drainer is event-woken —
# it drains while the queue is nonempty and parks on _spanq_wake when
# it runs dry, so a submitted span reaches the recent-span store in
# wakeup latency (~ms), not a fixed poll period.  The timeout below
# merely bounds the damage of a hypothetically missed wakeup.
_SPANQ_PARK_S = 0.5
_spanq_wake = threading.Event()
# written only by the drainer, read by submit(): True while the
# drainer is (about to be) parked — the ExecutionQueue idiom, so the
# token path pays one plain attribute read per span and an Event.set
# only on the empty->nonempty transition window
_spanq_parked = False
# exclusive access to the native queue for callers that need the
# drainer to keep its hands off (the spanq unit tests push non-Span
# probes; a concurrent drainer steal would both flake the test and
# poison _collected with foreign objects)
_spanq_pause = threading.Lock()


def _drain_native_spanq() -> None:
    """Move every natively queued span into the recent-span store
    (speed-limited, SpanDB-persisted).  Runs on the drainer thread and
    synchronously from flush(); spanq_drain's atomic exchange makes
    concurrent drains hand each span to exactly one caller."""
    from brpc_tpu import native_path
    fb = native_path._fastrpc_mod()
    if fb is None:
        return
    spans = fb.spanq_drain()
    if not spans:
        return
    from brpc_tpu.bvar.collector import get_or_create_limit
    from brpc_tpu.butil import hostcpu
    limit = get_or_create_limit("rpcz", 2000)
    t_cpu0 = time.thread_time()
    # same bounded-overhead contract as the Collector (the speed limit
    # drops the excess, keeping the EARLIEST spans — FIFO), but ONE
    # budget grab per drained batch: per-span grab() here held the GIL
    # for milliseconds on a 2000-span drain, stealing it from the very
    # token path this queue exists to protect
    kept = spans[:limit.grab_n(len(spans))]
    if kept:
        global _collect_seq
        with _collect_lock:
            for span in kept:
                _collect_seq += 1
                try:
                    span.seq = _collect_seq
                except AttributeError:
                    pass   # a foreign probe object on the native queue
            _collected.extend(kept)
        with _db_lock:
            if _db_dir is not None:
                for span in kept:
                    try:
                        _db_append_locked(span)
                    except OSError:
                        pass  # disk trouble must never break collection
    # span-submit host-CPU accounting (ISSUE 6) stays honest: the
    # heavyweight half now burns THIS thread, not the token path
    hostcpu.add("span_submit", (time.thread_time() - t_cpu0) * 1e6)


def _spanq_loop() -> None:
    """ExecutionQueue-style cadence (ISSUE 10, PR 9 follow-on d):
    drain while the native queue is nonempty, park on the wake event
    when it runs dry.  The parked/park-check ordering makes a missed
    wakeup impossible under the GIL's sequential consistency: the
    drainer publishes ``_spanq_parked = True`` BEFORE its final
    pending check, and submit() pushes BEFORE reading the flag — so
    either the drainer's check sees the span, or the submitter sees
    the flag and sets the event.  A spurious set (span drained between
    push and flag read) costs one empty drain."""
    global _spanq_parked
    from brpc_tpu import native_path
    while True:
        try:
            with _spanq_pause:
                _drain_native_spanq()
            fb = native_path._fastrpc_mod()
            if fb is not None and fb.spanq_pending():
                continue          # drain again: the queue refilled
            _spanq_parked = True
            try:
                if fb is not None and fb.spanq_pending():
                    continue      # raced a push; drain immediately
                _spanq_wake.wait(_SPANQ_PARK_S)
            finally:
                _spanq_parked = False
                _spanq_wake.clear()
        except Exception:
            time.sleep(0.05)   # a torn drain must never kill (or spin)
            #                    the drainer


def _ensure_spanq_drainer() -> None:
    global _spanq_thread
    with _spanq_mu:
        if _spanq_thread is None or not _spanq_thread.is_alive():
            _spanq_thread = threading.Thread(
                target=_spanq_loop, daemon=True, name="rpcz-spanq")
            _spanq_thread.start()


def submit(span: Span) -> None:
    if not _enabled or span is NULL_SPAN:
        return
    if not span.sampled:
        # the head-sampling decision was made at the TRACE root and
        # inherited (new_span); dropping here keeps whole traces —
        # re-rolling per span would leave a kept trace with holes
        return
    span.end_us = span.end_us or now_us()
    from brpc_tpu import native_path
    fb = native_path.spanq()
    if fb is not None:
        # ISSUE 9 hot path: one lock-free native push; everything
        # heavier happens on the rpcz-spanq drainer
        fb.spanq_push(span)
        # ISSUE 10: wake a parked drainer — one GIL-atomic flag read on
        # the common (drainer busy) path, an Event.set only on the
        # empty->nonempty transition (see _spanq_loop for the ordering
        # argument)
        if _spanq_parked:
            _spanq_wake.set()
        t = _spanq_thread
        if t is None or not t.is_alive():
            # covers first use AND a dead-but-non-None thread (a fork's
            # child inherits the module state but not the drainer)
            _ensure_spanq_drainer()
        return
    from brpc_tpu.bvar.collector import Collector, get_or_create_limit
    Collector.instance().submit(_SpanSample(span),
                                get_or_create_limit("rpcz", 2000),
                                family="rpcz")


def flush() -> None:
    """Synchronously land this thread's prior submissions in the
    recent-span store — drains the native span queue AND the rpcz
    Collector family (whichever path each span took)."""
    with _spanq_pause:
        _drain_native_spanq()
    from brpc_tpu.bvar.collector import Collector
    Collector.instance().flush(family="rpcz")


def recent_spans(limit: int = 100, trace_id: int | None = None) -> list[Span]:
    # observe our own prior submissions; flushing ONLY the rpcz family
    # (plus the native queue) keeps this (console) thread away from
    # other consumers' IO
    flush()
    with _collect_lock:
        spans = list(_collected)
    if trace_id is not None:
        spans = [s for s in spans if s.trace_id == trace_id]
    return spans[-limit:]


def spans_since(cursor: int, limit: int = 256,
                finished_only: bool = True) -> tuple[list[Span], int]:
    """Incremental pull for the fleet telemetry plane (ISSUE 20):
    collected spans with ``seq > cursor`` (oldest first, at most
    ``limit``) plus the store's current high-water seq.  A caller that
    re-pulls with the returned cursor sees each span exactly once —
    until the bounded ring evicts faster than it pulls, in which case
    the gap is simply skipped (the cursor is monotone, never rewound).
    ``finished_only`` drops still-open spans (end_us unset) — the
    telemetry contract ships only finished spans."""
    flush()
    with _collect_lock:
        hi = _collect_seq
        out = [s for s in _collected if getattr(s, "seq", 0) > cursor]
    if finished_only:
        out = [s for s in out if s.end_us]
    out.sort(key=lambda s: s.seq)
    return out[:max(0, int(limit))], hi


def span_to_dict(span: Span) -> dict:
    """The wire shape of one span — exactly the SpanDB record (so
    ``span_from_dict``/``load_disk_spans`` share one decode path)."""
    return {
        "trace_id": span.trace_id, "span_id": span.span_id,
        "parent_span_id": span.parent_span_id, "service": span.service,
        "method": span.method, "remote_side": span.remote_side,
        "start_us": span.start_us, "end_us": span.end_us,
        "request_size": span.request_size,
        "response_size": span.response_size,
        "error_code": span.error_code, "kind": span.kind,
        "recovered_from": span.recovered_from,
        "migrated_from": span.migrated_from,
        "annotations": list(span.annotations)}


def span_from_dict(rec: dict) -> Span | None:
    """Inverse of :func:`span_to_dict`; ``None`` on a malformed record
    (one bad span from a remote process must not kill the merge)."""
    try:
        rec = dict(rec)
        ann = [tuple(a) for a in rec.pop("annotations", ())]
        rec.pop("sampled", None)
        rec.pop("seq", None)
        return Span(annotations=ann, **rec)
    except (TypeError, ValueError, AttributeError):
        return None


def traceprintf(msg: str) -> None:
    """TRACEPRINTF analog: annotate the current span."""
    s = get_current_span()
    if s is not None:
        s.annotate(msg)


# ---- timeline reconstruction (the /rpcz?trace_id= tree view and
# rpc_press --dump-traces both render one trace as an indented,
# time-offset span tree) ----

def trace_tree(spans: list[Span]) -> list[tuple[int, int, Span]]:
    """Order one trace's spans as a tree: ``[(depth, offset_us, span)]``
    with offsets relative to the trace's earliest start.  Children sort
    under their parent by start time; a span whose parent was not
    collected (sampling off at that hop, eviction from the bounded
    store) surfaces as an extra root rather than disappearing."""
    spans = sorted(spans, key=lambda s: (s.start_us, s.span_id))
    if not spans:
        return []
    by_id = {s.span_id: s for s in spans}
    children: dict[int, list[Span]] = {}
    roots: list[Span] = []
    for s in spans:
        p = s.parent_span_id
        if p and p in by_id and p != s.span_id:
            children.setdefault(p, []).append(s)
        else:
            roots.append(s)
    t0 = spans[0].start_us
    out: list[tuple[int, int, Span]] = []

    def walk(s: Span, depth: int) -> None:
        out.append((depth, s.start_us - t0, s))
        for c in children.get(s.span_id, ()):
            walk(c, depth + 1)

    for r in roots:
        walk(r, 0)
    return out


def format_trace(spans: list[Span], indent: str = "  ") -> str:
    """Human-readable timeline for ONE trace: tree-ordered spans with
    relative start offsets, per-span latency, recovery links, and the
    annotations at their relative timestamps."""
    tree = trace_tree(spans)
    if not tree:
        return "no spans\n"
    t0 = min(s.start_us for _, _, s in tree)
    total = max(s.end_us for _, _, s in tree) - t0
    lines = [f"trace {tree[0][2].trace_id} — {len(tree)} spans, "
             f"{total}us total"]
    for depth, off, s in tree:
        pad = indent * depth
        link = f" recovered_from=span {s.recovered_from}" \
            if s.recovered_from else ""
        if s.migrated_from:
            link += f" migrated_from=span {s.migrated_from}"
        err = f" err={s.error_code}" if s.error_code else ""
        lines.append(
            f"{pad}+{off}us [{s.kind}] {s.service}.{s.method} "
            f"span={s.span_id} {s.latency_us}us{err}{link}"
            + (f" peer={s.remote_side}" if s.remote_side else ""))
        for t, msg in s.annotations:
            lines.append(f"{pad}{indent}@+{max(0, t - t0)}us {msg}")
    return "\n".join(lines) + "\n"


def slowest_traces(spans: list[Span], n: int = 3) -> list[list[Span]]:
    """Group `spans` by trace and return the n slowest traces (by their
    root span's latency; widest span when no root was collected),
    slowest first — the rpc_press --dump-traces selection."""
    by_trace: dict[int, list[Span]] = {}
    for s in spans:
        by_trace.setdefault(s.trace_id, []).append(s)

    def root_latency(group: list[Span]) -> int:
        ids = {s.span_id for s in group}
        roots = [s for s in group
                 if not s.parent_span_id or s.parent_span_id not in ids]
        return max(s.latency_us for s in roots or group)

    ranked = sorted(by_trace.values(), key=root_latency, reverse=True)
    return ranked[:n]
