"""brpc_tpu.serving — inference serving on the RPC/ICI stack.

Three cooperating pieces (see README "Serving"):

  * :class:`DynamicBatcher` (batcher.py) — deadline-aware dynamic
    batching of concurrent unary RPCs into bucket-padded tensor calls;
  * :class:`DecodeEngine` (engine.py) — continuous-batching
    autoregressive decode over a fixed slot pool with KV state leased
    from the ICI BlockPool (raw blocks, or paged sequences through a
    :class:`brpc_tpu.kvcache.KVCacheStore` for radix prefix reuse);
  * :func:`register_serving` (service.py) — server glue exposing
    ``Serving.Score`` (batched unary) and ``Serving.Generate``
    (streaming decode) plus the chunked-HTTP generate route;
  * :class:`EngineSupervisor` (supervisor.py) — step-loop watchdog,
    crash recovery (in-flight decode failover over the surviving KV
    cache) and the overload degradation ladder; its ``submit`` has the
    engine's signature so it drops into ``register_serving``
    unchanged.

Every live batcher/engine/supervisor self-registers here (weakly, by
name) so the ``/serving`` builtin-console page can render batch
occupancy, the slot map, shed/pad statistics, and supervisor state
without holding components alive.

GENERATION TIMELINE (ISSUE 5): every retired decode attempt (engine)
and every completed supervised generation (supervisor) appends a
summary record to a bounded ring here — request/trace ids, TTFT,
inter-token latency, prefill-skip, restart count — which the
``/serving/generations`` console page renders alongside the aggregate
``serving_ttft_us`` / ``serving_itl_us`` recorders.
"""
from __future__ import annotations

import threading
from brpc_tpu.butil.lockprof import InstrumentedLock
import weakref
from collections import deque

_reg_mu = InstrumentedLock("serving.registry")
_batchers: "weakref.WeakValueDictionary[str, object]" = \
    weakref.WeakValueDictionary()
_engines: "weakref.WeakValueDictionary[str, object]" = \
    weakref.WeakValueDictionary()
_supervisors: "weakref.WeakValueDictionary[str, object]" = \
    weakref.WeakValueDictionary()
_routers: "weakref.WeakValueDictionary[str, object]" = \
    weakref.WeakValueDictionary()


def _register_batcher(b) -> None:
    with _reg_mu:
        _batchers[b.name] = b


def _register_engine(e) -> None:
    with _reg_mu:
        _engines[e.name] = e


def _register_supervisor(s) -> None:
    with _reg_mu:
        _supervisors[s.name] = s


def _register_router(r) -> None:
    with _reg_mu:
        _routers[r.name] = r


def cluster_snapshot() -> dict:
    """Live routers' stats — the /cluster console page's data: per
    router the replica table (health / breaker / quarantine / ladder
    level), session counts, resume stats, and the gradient's per-level
    fire counters."""
    with _reg_mu:
        routers = dict(_routers)
    return {
        "routers": {name: r.stats() for name, r in sorted(routers.items())},
    }


def fleet_snapshot(points: int = 32) -> dict:
    """Live routers' fleet telemetry (ISSUE 20) — the /fleet console
    page's data: per router the collector state (pulls, bytes,
    tombstones), the windowed series rings, per-model scoreboard,
    canary ramp state and the SLO decision trail."""
    with _reg_mu:
        routers = dict(_routers)
    return {
        "routers": {name: r.fleet_snapshot(points)
                    for name, r in sorted(routers.items())},
    }


def fleet_trace_spans(trace_id: int) -> list:
    """Cross-process spans of one trace, fanned out through every live
    router (the /rpcz?trace_id= stitching read) — empty when no router
    is registered or nothing was collected."""
    with _reg_mu:
        routers = dict(_routers)
    merged: dict[tuple, object] = {}
    for r in routers.values():
        try:
            for s in r.trace_fanout(trace_id):
                merged.setdefault(
                    (s.trace_id, s.span_id, s.kind, s.start_us), s)
        except Exception:
            continue
    return list(merged.values())


def serving_snapshot() -> dict:
    """Live components' stats — the /serving console page's data."""
    with _reg_mu:
        batchers = dict(_batchers)
        engines = dict(_engines)
        supervisors = dict(_supervisors)
    return {
        "batchers": {name: b.stats() for name, b in sorted(batchers.items())},
        "engines": {name: e.stats() for name, e in sorted(engines.items())},
        "supervisors": {name: s.stats()
                        for name, s in sorted(supervisors.items())},
    }


# ---- recent-generation ring (the /serving/generations console page) ----

_GEN_KEEP = 256
_gen_mu = InstrumentedLock("serving.generations")
_recent_gens: deque = deque(maxlen=_GEN_KEEP)


def record_generation(rec: dict) -> None:
    """Append one finished generation/attempt summary (bounded ring)."""
    with _gen_mu:
        _recent_gens.append(rec)


def recent_generations(limit: int = 50) -> list[dict]:
    with _gen_mu:
        gens = list(_recent_gens)
    return gens[-limit:]


def generations_snapshot(limit: int = 50) -> dict:
    """The /serving/generations page data: aggregate TTFT/ITL
    percentiles from the global recorders, prefill-skip over the recent
    window, supervisor recovery counts, and the recent records
    themselves (newest last)."""
    from brpc_tpu.serving.engine import ITL_REC, TTFT_REC
    recent = recent_generations(limit)
    # skip-ratio over ENGINE attempt records only (they carry
    # prefix_hit); supervisor rows describe the same generations again
    # and would double-count every prompt in the denominator
    prompt = sum(r["prompt_len"] for r in recent if "prefix_hit" in r)
    hit = sum(r["prefix_hit"] for r in recent if "prefix_hit" in r)
    with _reg_mu:
        supervisors = dict(_supervisors)
    recoveries = sum(s.restarts_total.get_value()
                     for s in supervisors.values())
    # speculative-decoding acceptance over the recent window (ISSUE
    # 11): engine records carry per-generation accept_rate /
    # tokens_per_step when a draft proposer ran
    spec_rows = [r for r in recent if "accept_rate" in r]
    proposed = sum(r.get("spec_proposed", 0) for r in spec_rows)
    accepted = sum(r.get("spec_accepted", 0) for r in spec_rows)
    speculative = {
        "generations": len(spec_rows),
        "accept_rate": round(accepted / proposed, 4) if proposed
        else 0.0,
        "avg_tokens_per_step": round(
            sum(r["tokens_per_step"] for r in spec_rows)
            / len(spec_rows), 2) if spec_rows else 0.0,
    }
    return {
        "aggregates": {
            "speculative": speculative,
            "ttft_us": {
                "count": TTFT_REC.count(),
                "avg": round(TTFT_REC.latency(), 1),
                "p50": round(TTFT_REC.latency_percentile(0.5), 1),
                "p99": round(TTFT_REC.latency_percentile(0.99), 1),
            },
            "itl_us": {
                "count": ITL_REC.count(),
                "avg": round(ITL_REC.latency(), 1),
                "p50": round(ITL_REC.latency_percentile(0.5), 1),
                "p99": round(ITL_REC.latency_percentile(0.99), 1),
            },
            "prefill_skip_ratio": round(hit / prompt, 4) if prompt else 0.0,
            "recoveries": recoveries,
        },
        "recent": recent,
    }


from brpc_tpu.serving.batcher import DynamicBatcher  # noqa: E402,F401
from brpc_tpu.serving.engine import DecodeEngine  # noqa: E402,F401
from brpc_tpu.serving.service import (  # noqa: E402,F401
    ScoreClient, ServingService, http_generate_handler, register_serving,
)
from brpc_tpu.serving.supervisor import EngineSupervisor  # noqa: E402,F401
from brpc_tpu.serving.ladder import OverloadLadder  # noqa: E402,F401
from brpc_tpu.serving.speculative import (  # noqa: E402,F401
    DraftModelProposer, DraftProposer, NGramProposer, as_proposer,
)
from brpc_tpu.serving.router import (  # noqa: E402,F401
    ClusterRouter, ReplicaHandle, RouterClient, RouterService,
    SessionTable, register_router,
)
from brpc_tpu.serving.session_wal import SessionWAL  # noqa: E402,F401
from brpc_tpu.serving.cluster_control import (  # noqa: E402,F401
    CLUSTER_SERVICE, ClusterControlService, register_cluster_control,
)
from brpc_tpu.serving.modelplane import (  # noqa: E402,F401
    DEFAULT_MODEL, CanarySplit, ModelCatalog, ModelMetrics,
    ReplicaDeployments, cluster_deploy, deployment_key,
    model_fingerprint, split_deployment_key,
)
from brpc_tpu.serving.telemetry import (  # noqa: E402,F401
    TELEMETRY_SERVICE, FleetCollector, TelemetryService,
    register_telemetry, telemetry_snapshot,
)
from brpc_tpu.serving.slo import Objective, SLOEngine  # noqa: E402,F401
