"""brpc_tpu.serving — inference serving on the RPC/ICI stack.

Three cooperating pieces (see README "Serving"):

  * :class:`DynamicBatcher` (batcher.py) — deadline-aware dynamic
    batching of concurrent unary RPCs into bucket-padded tensor calls;
  * :class:`DecodeEngine` (engine.py) — continuous-batching
    autoregressive decode over a fixed slot pool with KV state leased
    from the ICI BlockPool (raw blocks, or paged sequences through a
    :class:`brpc_tpu.kvcache.KVCacheStore` for radix prefix reuse);
  * :func:`register_serving` (service.py) — server glue exposing
    ``Serving.Score`` (batched unary) and ``Serving.Generate``
    (streaming decode) plus the chunked-HTTP generate route;
  * :class:`EngineSupervisor` (supervisor.py) — step-loop watchdog,
    crash recovery (in-flight decode failover over the surviving KV
    cache) and the overload degradation ladder; its ``submit`` has the
    engine's signature so it drops into ``register_serving``
    unchanged.

Every live batcher/engine/supervisor self-registers here (weakly, by
name) so the ``/serving`` builtin-console page can render batch
occupancy, the slot map, shed/pad statistics, and supervisor state
without holding components alive.
"""
from __future__ import annotations

import threading
import weakref

_reg_mu = threading.Lock()
_batchers: "weakref.WeakValueDictionary[str, object]" = \
    weakref.WeakValueDictionary()
_engines: "weakref.WeakValueDictionary[str, object]" = \
    weakref.WeakValueDictionary()
_supervisors: "weakref.WeakValueDictionary[str, object]" = \
    weakref.WeakValueDictionary()


def _register_batcher(b) -> None:
    with _reg_mu:
        _batchers[b.name] = b


def _register_engine(e) -> None:
    with _reg_mu:
        _engines[e.name] = e


def _register_supervisor(s) -> None:
    with _reg_mu:
        _supervisors[s.name] = s


def serving_snapshot() -> dict:
    """Live components' stats — the /serving console page's data."""
    with _reg_mu:
        batchers = dict(_batchers)
        engines = dict(_engines)
        supervisors = dict(_supervisors)
    return {
        "batchers": {name: b.stats() for name, b in sorted(batchers.items())},
        "engines": {name: e.stats() for name, e in sorted(engines.items())},
        "supervisors": {name: s.stats()
                        for name, s in sorted(supervisors.items())},
    }


from brpc_tpu.serving.batcher import DynamicBatcher  # noqa: E402,F401
from brpc_tpu.serving.engine import DecodeEngine  # noqa: E402,F401
from brpc_tpu.serving.service import (  # noqa: E402,F401
    ServingService, http_generate_handler, register_serving,
)
from brpc_tpu.serving.supervisor import EngineSupervisor  # noqa: E402,F401
