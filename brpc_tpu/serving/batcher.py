"""Deadline-aware dynamic batcher — coalesces concurrent unary RPCs into
batched tensor calls.

"RPC Considered Harmful" (PAPERS.md) quantifies why per-request tensor
RPC wastes the fabric: each call pays the full dispatch overhead for one
row of work.  The batcher gathers concurrent requests under a
``max_batch_size`` / ``max_delay_us`` policy, pads them to a SMALL FIXED
SET of bucket shapes so the jit cache is hit (never a per-shape
recompile), runs the batch through one user-supplied jitted function,
and scatters the rows back to each caller.

Admission is deadline-aware and rides the existing limiter/ELIMIT
machinery rather than a new error path: a queued request whose
Controller deadline would expire before the predicted batch completion
(window wait + EMA batch execution time x batches ahead) is shed
IMMEDIATELY with ELIMIT — the caller learns "would have missed" in
microseconds instead of burning a queue slot to learn it at its
deadline.  An optional concurrency limiter (the same
``create_limiter`` specs servers use: int, "auto", "timeout[:ms]")
gates queue depth the same way.

BROWNOUT (``brownout`` attribute, set by an EngineSupervisor's
degradation ladder): at level >= 1 the LOWEST-priority lane —
deadline-less requests, the ones EDF already ranks last — is shed at
admission with ELIMIT, so under overload the queue carries only work
someone is waiting on with a deadline.  Shedding at admission (not at
formation) keeps the refusal latency in microseconds, the same
philosophy as the deadline-aware shed.

PRIORITY LANES: batch formation is earliest-deadline-first within the
batching window, not FIFO.  When more requests are queued than one
batch holds, the FIFO head always takes one seat (bounded wait for
everyone — a deadline-less request can never be starved by a stream
of deadlined arrivals) and the nearest deadlines fill the rest
(deadline-less requests rank last, FIFO among themselves); a request
that jumps an earlier-enqueued one counts as a lane promotion on
/vars.

PREFIX-AWARE PREFILL (``prefix_cache=``, a
:class:`~brpc_tpu.kvcache.KVCacheStore`): token prompts whose prefix
the paged KV cache already holds are trimmed to their uncached SUFFIX
at batch formation — the batch computes (and pads) only what the
cache can't serve, so a 90%-shared workload rides smaller length
buckets and the skip ratio shows up per batcher on /vars.  The
matched pages are PINNED (``acquire_prefix``/``release``) for the
batch's lifetime, so eviction under pool pressure can never free the
prefix KV the trim relies on, and a ``batch_fn(padded, offsets)``
that accepts a second argument receives each row's start position
(rows are suffixes — a position-dependent scorer needs the offset).

Instrumented per batcher on /vars (and the /serving console page):
batch-size IntRecorder, queue-delay LatencyRecorder, pad-waste ratio,
shed counter, lane promotions, prefix-skip ratio.
"""
from __future__ import annotations

import re
import threading
import time
from typing import Callable, Optional, Sequence

import numpy as np

from brpc_tpu import errors, fault, native_path, rpcz
from brpc_tpu.butil import hostcpu
from brpc_tpu.butil.lockprof import InstrumentedLock
from brpc_tpu.bvar import Adder, IntRecorder, LatencyRecorder, PassiveStatus

# default sequence-length buckets: small fixed ladder so any raw length
# maps to one of a handful of compiled shapes
DEFAULT_LENGTH_BUCKETS = (16, 64, 256, 1024, 4096)


def required_positional_args(fn) -> int:
    """How many REQUIRED positional parameters `fn` takes (-1 when its
    signature is unreadable).  Used to decide whether a user function
    gets the optional extra array (batcher offsets / engine page
    table): a parameter WITH a default is not counted — passing the
    extra into e.g. ``temperature=1.0`` would silently corrupt compute
    — and ``*args`` counts for nothing (pass the explicit flag for
    those)."""
    import inspect
    try:
        params = inspect.signature(fn).parameters.values()
    except (TypeError, ValueError):
        return -1
    return sum(1 for p in params
               if p.kind in (p.POSITIONAL_ONLY,
                             p.POSITIONAL_OR_KEYWORD)
               and p.default is p.empty)


def _bucket_up(n: int, buckets: Sequence[int]) -> Optional[int]:
    for b in buckets:
        if n <= b:
            return b
    return None


def _default_batch_buckets(max_batch_size: int) -> tuple:
    out = []
    b = 1
    while b < max_batch_size:
        out.append(b)
        b *= 2
    out.append(max_batch_size)
    return tuple(out)


class _Pending:
    """One queued request: the padded-batch row it will occupy plus an
    exactly-once completion (error or result, never neither, never
    both)."""

    __slots__ = ("item", "length", "skip", "enqueue_t", "deadline_s",
                 "span", "_fire", "_fired", "_mu")

    def __init__(self, item: np.ndarray, length: int,
                 deadline_s: Optional[float],
                 fire: Callable[[int, str, object], None]):
        self.item = item
        self.length = length
        self.skip = 0              # prefix tokens served from KV cache
        self.enqueue_t = time.monotonic()
        self.deadline_s = deadline_s
        # per-request batch span (ISSUE 5): opened at enqueue under the
        # caller's trace (the RPC ingress span), so queue delay is the
        # span's head and shed/promotion/trim decisions annotate it;
        # NULL_SPAN when rpcz is off
        self.span = rpcz.NULL_SPAN
        self._fire = fire
        self._fired = False
        self._mu = threading.Lock()

    def complete(self, code: int, text: str, result) -> None:
        with self._mu:
            if self._fired:
                return
            self._fired = True
        span = self.span
        if span is not rpcz.NULL_SPAN:
            # exactly-once completion also finalizes the span exactly
            # once (the _fired guard above is the submission guard)
            if code:
                span.error_code = code
                span.annotate(f"completed with error {code}: {text}")
            rpcz.submit(span)
        try:
            self._fire(code, text, result)
        except Exception:
            # a raising completion callback must never kill the batch
            # drainer (it would wedge every other queued request); the
            # callback owner's bug is logged, the loop lives on
            import logging
            logging.getLogger(__name__).exception(
                "batcher completion callback raised")


class _Future:
    """Local (non-RPC) completion for submit_wait()."""

    def __init__(self):
        self._ev = threading.Event()
        self.code = 0
        self.text = ""
        self.result = None

    def fire(self, code: int, text: str, result) -> None:
        self.code, self.text, self.result = code, text, result
        self._ev.set()

    def wait(self, timeout_s: float):
        if not self._ev.wait(timeout_s):
            raise errors.RpcError(errors.ERPCTIMEDOUT,
                                  "batcher result not ready")
        if self.code:
            raise errors.RpcError(self.code, self.text)
        return self.result


class DynamicBatcher:
    """Per-method dynamic batcher.

    ``batch_fn(padded)`` receives a ``[batch_bucket, length_bucket]``
    array (row i = request i's item, zero-padded) and returns either a
    per-row vector (``[batch]``) or a padded matrix (``[batch,
    length_bucket]``, trimmed back to each request's raw length on
    scatter).  Supply a ``jax.jit``-wrapped function: because inputs are
    always bucket shapes, it compiles once per bucket and never again.
    """

    def __init__(self, batch_fn: Callable, *,
                 max_batch_size: int = 16,
                 max_delay_us: int = 2000,
                 batch_buckets: Optional[Sequence[int]] = None,
                 length_buckets: Sequence[int] = DEFAULT_LENGTH_BUCKETS,
                 limiter=None,
                 prefix_cache=None,
                 pass_offsets: Optional[bool] = None,
                 name: str = "default",
                 dtype=np.float32,
                 padded_output: Optional[bool] = None,
                 eager: bool = False):
        if max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        # a ModelRunner instance cannot exist unless its module is
        # already imported — the sys.modules probe keeps plain-numpy
        # batchers from paying the models-package (jax) import
        import sys as _sys
        _runner_mod = _sys.modules.get("brpc_tpu.models.runner")
        if _runner_mod is not None and \
                isinstance(batch_fn, _runner_mod.ModelRunner):
            # Serving.Score over a REAL model (ISSUE 10): a ModelRunner
            # drops in as the batch_fn — its dense scoring path (the
            # flash-kernel forward) computes per-position next-token
            # ids, trimmed back per row by the padded-output scatter.
            # With a prefix cache the 2-arg offsets variant rides the
            # formation-time trim exactly like any other offset-aware
            # batch_fn.
            batch_fn = (batch_fn.score_with_offsets
                        if prefix_cache is not None else batch_fn.score)
        self.batch_fn = batch_fn
        self.max_batch_size = int(max_batch_size)
        self.max_delay_us = int(max_delay_us)
        self.batch_buckets = tuple(sorted(
            batch_buckets or _default_batch_buckets(max_batch_size)))
        if self.batch_buckets[-1] < self.max_batch_size:
            raise ValueError("largest batch bucket must cover "
                             "max_batch_size")
        self.length_buckets = tuple(sorted(length_buckets))
        self.name = name
        self.dtype = np.dtype(dtype)
        # How to scatter batch_fn's output back to callers:
        #   True  — output is [batch, length_bucket]: trim row i to the
        #           request's raw length;
        #   False — output rows are per-request values of fixed width
        #           (or scalars): hand row i back whole;
        #   None  — infer per batch (trim iff the trailing dim equals
        #           the length bucket).  Pass it explicitly when a
        #           fixed-width output could COINCIDE with a length
        #           bucket — the heuristic cannot tell those apart and
        #           would silently truncate.
        self.padded_output = padded_output
        if limiter is not None:
            from brpc_tpu.policy.concurrency_limiter import create_limiter
            limiter = create_limiter(limiter)
        self.limiter = limiter
        # a KVCacheStore (or anything with probe/acquire_prefix/
        # release): items are trimmed to their uncached suffix at batch
        # formation with the matched pages pinned for the batch's
        # lifetime (see module docstring)
        self.prefix_cache = prefix_cache
        # a batch_fn with TWO required positionals receives per-row
        # start offsets alongside the suffix matrix (needed for
        # position-dependent compute); pass_offsets overrides the
        # detection for *args functions or optional-parameter shapes
        if pass_offsets is not None:
            self._fn_wants_offsets = bool(pass_offsets)
        else:
            self._fn_wants_offsets = (
                prefix_cache is not None
                and required_positional_args(batch_fn) >= 2)

        safe = re.sub(r"\W", "_", name)
        # record the EXACT names exposed below so close() hides only
        # this batcher's variables — a prefix wildcard would also strip
        # a sibling component whose name merely starts with ours
        from brpc_tpu.bvar.variable import exposed_variables
        _pre_bvars = set(exposed_variables(f"serving_{safe}*"))
        self.batch_size_rec = IntRecorder(f"serving_{safe}_batch_size")
        self.queue_delay_rec = LatencyRecorder(
            f"serving_{safe}_queue_delay")
        self.shed = Adder(f"serving_{safe}_shed")
        self.brownout_shed = Adder(f"serving_{safe}_brownout_shed")
        self.n_batches = Adder(f"serving_{safe}_batches")
        self.n_completed = Adder(f"serving_{safe}_completed")
        self.n_bypassed = Adder(f"serving_{safe}_bypassed")
        self.n_errors = Adder(f"serving_{safe}_errors")
        self.lane_promotions = Adder(f"serving_{safe}_lane_promotions")
        self._pad_elems = Adder()    # padded-but-unused elements
        self._real_elems = Adder()   # useful elements
        self._skip_elems = Adder()   # prefix elements served from cache
        self._seen_elems = Adder()   # total elements offered
        PassiveStatus(self._pad_waste).expose(
            f"serving_{safe}_pad_waste_ratio")
        PassiveStatus(self._prefix_skip_ratio).expose(
            f"serving_{safe}_prefix_skip_ratio")
        self._bvar_names = [n for n in exposed_variables(f"serving_{safe}*")
                            if n not in _pre_bvars]

        # EAGER mode (ISSUE 13, the PS surface's latency shape): the
        # batching WINDOW exists to gather concurrency, and when the
        # system is idle it is pure added latency — measured ~1ms per
        # request on CPU loopback (200us condvar timeout + GIL-contended
        # wakeups).  With eager=True:
        #   * an arrival finding the queue EMPTY and no batch executing
        #     runs INLINE on the submitting thread — batch of one, zero
        #     cross-thread hops (the cut-through);
        #   * the drainer forms whatever is queued IMMEDIATELY (no
        #     window wait) — coalescing comes from accumulation while
        #     the previous batch executes, the continuous-batching
        #     discipline (vLLM's shape): under load the drainer is
        #     always busy, so arrivals pile up and batches stay large.
        # Default False: generative scoring keeps the windowed policy.
        self.eager = bool(eager)
        # one batch in flight at a time in eager mode (inline OR
        # drainer — batch_fns keep the windowed mode's serial-execution
        # contract); guarded by self._cv's lock
        self._executing = False

        # overload-ladder level (0 = healthy), written by a supervisor;
        # read once per enqueue — plain attribute, GIL-atomic
        self.brownout = 0

        # the batcher queue lock is a NAMED hot lock (ISSUE 6): every
        # enqueue/formation contends here, so its wait/hold times ride
        # the lock-contention ledger (/hotspots/locks)
        self._cv = threading.Condition(InstrumentedLock("batcher.queue"))
        self._q: list[_Pending] = []
        self._exec_ema_s = 0.0
        self._running = True
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"serving-batcher-{safe}")
        self._thread.start()
        from brpc_tpu import serving as _serving
        _serving._register_batcher(self)

    # ---- the idle cut-through claim (eager mode) ----

    def try_claim_idle(self) -> bool:
        """Claim the execution slot for ONE request a caller will serve
        OUTSIDE the batcher (the PS handler bypass): succeeds only in
        eager mode, with no queue, no batch in flight, no brownout
        (degraded batchers must route everything through admission so
        the shed policy applies), and the batcher still running.  While
        claimed, concurrent arrivals queue and coalesce behind the
        bypassed request exactly as behind an inline cut-through batch.
        Pair with :meth:`release_idle`."""
        if not self.eager or self.brownout >= 1:
            return False
        with self._cv:
            if not self._running or self._q or self._executing:
                return False
            self._executing = True
        self.n_bypassed.add(1)
        return True

    def release_idle(self) -> None:
        with self._cv:
            self._executing = False
            self._cv.notify_all()

    # ---- admission ----

    def submit(self, cntl, item, transform: Optional[Callable] = None,
               ) -> None:
        """Server-handler entry: defers the RPC, enqueues the item, and
        completes the call from the batch drainer.  The request's
        deadline is read off the Controller's request meta (timeout_ms);
        ``transform(row)`` maps the scattered row to the response
        object."""
        done = cntl.defer()

        def fire(code: int, text: str, result) -> None:
            if code:
                cntl.set_failed(code, text)
                done(None)
                return
            if transform is not None:
                # a raising transform must still complete the RPC — the
                # client gets a definite EINTERNAL instead of a timeout
                try:
                    result = transform(result)
                except Exception as e:
                    cntl.set_failed(errors.EINTERNAL,
                                    f"response transform failed: "
                                    f"{type(e).__name__}: {e}")
                    done(None)
                    return
            done(result)

        meta = cntl.request_meta
        tmo_ms = meta.timeout_ms if meta is not None else 0
        deadline_s = (time.monotonic() + tmo_ms / 1e3) if tmo_ms > 0 \
            else None
        self.enqueue(item, fire, deadline_s=deadline_s)

    def submit_wait(self, item, timeout_s: float = 30.0,
                    deadline_s: Optional[float] = None):
        """Local blocking submission (tests, tools, non-RPC callers):
        returns the scattered row or raises RpcError."""
        fut = _Future()
        self.enqueue(item, fut.fire, deadline_s=deadline_s)
        return fut.wait(timeout_s)

    def enqueue(self, item, fire: Callable[[int, str, object], None],
                deadline_s: Optional[float] = None) -> None:
        """Core admission: validates the item, predicts completion, and
        either queues or sheds.  ``fire(code, text, result)`` runs
        exactly once."""
        arr = np.asarray(item, dtype=self.dtype)
        if arr.ndim == 0:
            arr = arr.reshape(1)
        p = _Pending(arr, 0, deadline_s, fire)
        # spans inherit the enqueuing thread's trace (the RPC ingress
        # span when coming through submit()); assigned before ANY
        # complete() path so every outcome — shed, reject, scatter —
        # finalizes it
        p.span = rpcz.child_span("batch", "Serving", self.name)
        if arr.ndim != 1:
            p.complete(errors.EREQUEST,
                       f"batcher items must be 1-D, got shape {arr.shape}",
                       None)
            self.n_errors.add(1)
            return
        p.length = arr.shape[0]
        if _bucket_up(p.length, self.length_buckets) is None:
            # an over-length item is still admissible when the prefix
            # cache holds enough of it that the SUFFIX fits a bucket
            # (advisory probe here; the binding, page-pinning trim
            # happens at batch formation)
            fits = False
            if self.prefix_cache is not None and p.length > 1:
                try:
                    hit = int(self.prefix_cache.probe(arr))
                except Exception:
                    hit = 0
                hit = max(0, min(hit, p.length - 1))
                fits = _bucket_up(p.length - hit,
                                  self.length_buckets) is not None
            if not fits:
                p.complete(errors.EREQUEST,
                           f"item length {p.length} exceeds largest "
                           f"bucket {self.length_buckets[-1]}", None)
                self.n_errors.add(1)
                return
        shed_code = 0
        shed_text = ""
        brownout = 0
        inline = False
        with self._cv:
            if not self._running:
                shed_code, shed_text = errors.ELOGOFF, "batcher closed"
            elif self.brownout >= 1 and p.deadline_s is None:
                # degradation ladder level >= 1: the lowest-priority
                # lane (deadline-less — EDF already ranks it last) is
                # refused at the door so the queue drains toward work
                # with a deadline someone is actually waiting out
                shed_code = errors.ELIMIT
                shed_text = (f"brownout level {self.brownout}: "
                             f"lowest-priority lane shed")
                brownout = 1
            elif self.limiter is not None and not self.limiter.on_requested(
                    len(self._q) + 1):
                # the SAME admission machinery servers use: limiter said
                # no -> ELIMIT, counted as a shed
                shed_code = errors.ELIMIT
                shed_text = "batcher queue limiter rejected the request"
            elif p.deadline_s is not None:
                # predicted completion: the full batching window (worst
                # case for a fresh queue) plus one EMA execution per
                # batch already ahead of us, plus our own.  Eager mode
                # never waits the window (cut-through / immediate
                # formation), so charging it would spuriously shed
                # tight-deadline requests an idle batcher would serve
                # well inside their budget
                batches_ahead = len(self._q) // self.max_batch_size
                window_s = 0.0 if self.eager else self.max_delay_us / 1e6
                predicted_s = (window_s +
                               (batches_ahead + 1) *
                               max(self._exec_ema_s, 0.0))
                if p.deadline_s < p.enqueue_t + predicted_s:
                    shed_code = errors.ELIMIT
                    shed_text = (
                        f"deadline-aware shed: deadline in "
                        f"{(p.deadline_s - p.enqueue_t) * 1e3:.1f}ms but "
                        f"predicted batch completion in "
                        f"{predicted_s * 1e3:.1f}ms")
            if shed_code == 0:
                if self.eager and not self._q and not self._executing:
                    # cut-through: the system is idle, so this request
                    # IS the batch — run it on the submitting thread,
                    # zero cross-thread hops (claims the execution slot
                    # under the lock; concurrent arrivals queue for the
                    # drainer and coalesce behind us)
                    self._executing = True
                    inline = True
                else:
                    self._q.append(p)
                    self._cv.notify()
        if shed_code != 0:
            if shed_code == errors.ELIMIT:
                self.shed.add(1)
                if brownout:
                    self.brownout_shed.add(1)
                if self.limiter is not None and not brownout:
                    # a brownout shed never consumed a limiter slot
                    self.limiter.on_responded(errors.ELIMIT, 0)
            self.n_errors.add(1)
            p.complete(shed_code, shed_text, None)
            return
        if inline:
            try:
                self._run_batch([p])
            except Exception:
                import logging
                logging.getLogger(__name__).exception(
                    "inline batch execution failed")
                p.complete(errors.EINTERNAL, "batch drainer error", None)
            finally:
                with self._cv:
                    self._executing = False
                    self._cv.notify_all()

    # ---- the batch loop ----

    def _loop(self) -> None:
        while True:
            with self._cv:
                while True:
                    if self.eager and self._executing:
                        # park while an inline (or our own previous)
                        # batch executes — one batch in flight, arrivals
                        # accumulate into the NEXT batch.  Checked even
                        # during shutdown: the close() flush must not
                        # run batch_fn concurrently with an in-flight
                        # inline batch (the serial-execution contract);
                        # the inline finally-block always clears the
                        # slot and notifies, so this wait is bounded
                        self._cv.wait()
                        continue
                    if self._running and not self._q:
                        self._cv.wait()
                        continue
                    break
                if not self._q:
                    if not self._running:
                        return
                    continue
                if not self.eager:
                    # batch window: first-enqueued request anchors the
                    # delay.  Eager mode skips the window entirely —
                    # whatever queued while the last batch executed IS
                    # the batch (continuous-batching accumulation).
                    deadline_t = self._q[0].enqueue_t \
                        + self.max_delay_us / 1e6
                    while self._running and \
                            len(self._q) < self.max_batch_size:
                        rem = deadline_t - time.monotonic()
                        if rem <= 0:
                            break
                        self._cv.wait(rem)
                batch = self._form_batch_locked()
                if batch and self.eager:
                    self._executing = True
            if not batch:
                continue
            try:
                self._run_batch(batch)
            except Exception:
                # belt over _run_batch's own error handling: the drainer
                # thread must survive ANY failure or the batcher wedges
                import logging
                logging.getLogger(__name__).exception(
                    "batch drainer iteration failed")
                for p in batch:
                    p.complete(errors.EINTERNAL, "batch drainer error",
                               None)
            finally:
                if self.eager:
                    with self._cv:
                        self._executing = False
                        self._cv.notify_all()

    def _form_batch_locked(self) -> list[_Pending]:
        """Pick this batch's members: earliest-deadline-first among the
        queued requests (priority lanes), FIFO among equals and the
        deadline-less.  A member selected over an earlier-enqueued
        request that stays queued counts as one lane promotion."""
        if len(self._q) <= self.max_batch_size:
            batch, self._q = self._q, []
            return batch
        # the FIFO head ALWAYS takes one seat: the queue front advances
        # every batch, so a deadline-less request has bounded wait even
        # under a sustained stream of deadlined arrivals (EDF alone
        # would starve it)
        order = sorted(
            range(1, len(self._q)),
            key=lambda i: (self._q[i].deadline_s
                           if self._q[i].deadline_s is not None
                           else float("inf"), i))
        taken = {0} | set(order[: self.max_batch_size - 1])
        take = sorted(taken)
        first_left = min(i for i in range(len(self._q))
                         if i not in taken)
        promoted = sum(1 for i in take if i > first_left)
        if promoted:
            self.lane_promotions.add(promoted)
            for i in take:
                if i > first_left and \
                        self._q[i].span is not rpcz.NULL_SPAN:
                    self._q[i].span.annotate(
                        "lane promotion: EDF selected this request "
                        "ahead of an earlier-enqueued one")
        batch = [self._q[i] for i in take]
        for i in reversed(take):
            del self._q[i]
        return batch

    def _run_batch(self, batch: list[_Pending]) -> None:
        # per-stage host-CPU accounting (ISSUE 6): everything this
        # method burns on the drainer thread EXCEPT the user batch_fn
        # call (timed separately in _execute) is batch-formation host
        # work — the de-GIL target ROADMAP item 4 needs sized
        t_cpu0 = time.thread_time()
        self._fn_cpu_s = 0.0
        try:
            self._run_batch_inner(batch)
        finally:
            hostcpu.add("batch_formation",
                        (time.thread_time() - t_cpu0 - self._fn_cpu_s)
                        * 1e6)
            hostcpu.add("model_compute", self._fn_cpu_s * 1e6)

    def _run_batch_inner(self, batch: list[_Pending]) -> None:
        now = time.monotonic()
        live: list[_Pending] = []
        for p in batch:
            if p.deadline_s is not None and p.deadline_s < now:
                # expired while queued (a burst pushed it past its
                # deadline): shed at dequeue rather than computing a row
                # nobody is waiting for
                self.shed.add(1)
                self.n_errors.add(1)
                if self.limiter is not None:
                    self.limiter.on_responded(errors.ELIMIT, 0)
                p.complete(errors.ELIMIT,
                           "deadline expired before batch formation", None)
            else:
                qd_us = int((now - p.enqueue_t) * 1e6)
                self.queue_delay_rec.add(qd_us)
                if p.span is not rpcz.NULL_SPAN:
                    p.span.annotate(f"batch formed: queue_delay_us={qd_us}"
                                    f" members={len(batch)}")
                live.append(p)
        if not live:
            return
        pinned: list = []
        try:
            live = self._trim_prefixes(live, pinned)
            if live:
                self._execute(live)
        finally:
            # the pinned prefix pages outlive the compute, never less:
            # eviction cannot free KV a row's trim relied on mid-batch
            if pinned and self.prefix_cache is not None:
                try:
                    self.prefix_cache.release(pinned)
                except Exception:
                    import logging
                    logging.getLogger(__name__).exception(
                        "prefix page release failed")

    def _trim_prefixes(self, live: list[_Pending], pinned: list) -> list:
        """Formation-time prefix trim: pin each item's cached prefix
        pages and keep only its uncached suffix for compute.  A row
        whose suffix no longer fits any bucket (the advisory enqueue
        probe's pages were evicted since) completes with a definite
        error instead of computing garbage."""
        if self.prefix_cache is None:
            return live
        kept = []
        for p in live:
            hit, pages = 0, []
            if p.length > 1:
                try:
                    hit, pages = self.prefix_cache.acquire_prefix(p.item)
                except Exception:
                    hit, pages = 0, []
            pinned.extend(pages)
            hit = max(0, min(hit, p.length - 1))
            if hit:
                if p.span is not rpcz.NULL_SPAN:
                    p.span.annotate(
                        f"kv prefix trim: {hit}/{p.length} tokens served "
                        f"from {len(pages)} pinned cached pages")
                p.skip = hit
                p.item = p.item[hit:]
                p.length -= hit
            if _bucket_up(p.length, self.length_buckets) is None:
                self.n_errors.add(1)
                if self.limiter is not None:
                    self.limiter.on_responded(errors.EREQUEST, 0)
                p.complete(errors.EREQUEST,
                           f"suffix length {p.length} exceeds largest "
                           f"bucket (cached prefix evicted since "
                           f"admission)", None)
            else:
                kept.append(p)
        return kept

    def _form_batch(self, live: list[_Pending], bshape: int,
                    lbucket: int) -> np.ndarray:
        """Formation gather/pad — MECHANISM only (bucket choice, EDF
        lanes and shed policy are decided above, in Python, where
        policy lives): returns the (bshape, lbucket) padded batch with
        live[i] scattered into row i.

        Native path (ISSUE 9): zero-fill + every row memcpy run as ONE
        GIL-released native pass, so concurrent submitters keep running
        through formation.  Fallback: the numpy per-row scatter loop.
        The `batch_assembly` microbench rung hammers THIS method."""
        if native_path.batch_pad_available():
            padded = np.empty((bshape, lbucket), dtype=self.dtype)
            # enqueue() already coerced every item to a 1-D array of
            # self.dtype, so ascontiguousarray is a no-op for the
            # common case (suffix trims of contiguous arrays stay
            # contiguous); it protects the native memcpy from a strided
            # array a caller snuck through
            rows = [np.ascontiguousarray(p.item) for p in live]
            native_path.batch_pad(padded, rows,
                                  [p.length for p in live])
            return padded
        padded = np.zeros((bshape, lbucket), dtype=self.dtype)
        for i, p in enumerate(live):
            padded[i, : p.length] = p.item
        return padded

    def _execute(self, live: list[_Pending]) -> None:
        n = len(live)
        bshape = _bucket_up(n, self.batch_buckets)
        lbucket = _bucket_up(max(p.length for p in live),
                             self.length_buckets)
        padded = self._form_batch(live, bshape, lbucket)
        real = 0
        skipped = 0
        for p in live:
            real += p.length
            skipped += p.skip
        self._real_elems.add(real)
        self._pad_elems.add(bshape * lbucket - real)
        # skip metrics count EXECUTED rows only (like pad-waste): a
        # shed or rejected request saved no compute
        self._skip_elems.add(skipped)
        self._seen_elems.add(real + skipped)
        self.batch_size_rec.add(n)
        self.n_batches.add(1)
        t0 = time.monotonic()
        t_fn_cpu = time.thread_time()
        try:
            if fault.ENABLED and fault.hit(
                    "serving.batch", name=self.name, batch=n) is not None:
                raise RuntimeError("injected mid-batch failure")
            if self._fn_wants_offsets:
                offsets = np.zeros((bshape,), np.int32)
                for i, p in enumerate(live):
                    offsets[i] = p.skip
                out = np.asarray(self.batch_fn(padded, offsets))
            else:
                out = np.asarray(self.batch_fn(padded))
        except Exception as e:
            self._fn_cpu_s = time.thread_time() - t_fn_cpu
            # a failed batch completes EVERY member exactly once with a
            # definite error — never a hang, never a partial scatter
            self.n_errors.add(n)
            for p in live:
                if self.limiter is not None:
                    self.limiter.on_responded(errors.EINTERNAL, 0)
                p.complete(errors.EINTERNAL,
                           f"batch execution failed: "
                           f"{type(e).__name__}: {e}", None)
            return
        self._fn_cpu_s = time.thread_time() - t_fn_cpu
        dt = time.monotonic() - t0
        self._exec_ema_s = dt if self._exec_ema_s == 0.0 \
            else 0.7 * self._exec_ema_s + 0.3 * dt
        trim = self.padded_output if self.padded_output is not None \
            else (out.ndim >= 2 and out.shape[-1] == lbucket)
        for i, p in enumerate(live):
            row = out[i, : p.length] if trim else out[i]
            lat_us = int((time.monotonic() - p.enqueue_t) * 1e6)
            if self.limiter is not None:
                self.limiter.on_responded(0, lat_us)
            self.n_completed.add(1)
            p.complete(0, "", row)

    # ---- lifecycle / introspection ----

    def close(self, timeout_s: float = 10.0) -> None:
        """Stop accepting; the drainer flushes queued batches (no window
        wait) and exits."""
        with self._cv:
            self._running = False
            self._cv.notify_all()
        self._thread.join(timeout_s)
        # anything still queued (drainer died / timeout): definite error
        with self._cv:
            leftovers, self._q = self._q, []
        for p in leftovers:
            p.complete(errors.ELOGOFF, "batcher closed", None)
        # unpin from the global bvar registry: the exposed PassiveStatus
        # objects hold bound methods, which would keep a closed batcher
        # (and everything its batch_fn captures) alive forever and
        # defeat the serving registry's weakrefs
        from brpc_tpu.bvar.variable import find_exposed
        for n in self._bvar_names:
            v = find_exposed(n)
            if v is not None:
                v.hide()

    def _pad_waste(self) -> float:
        real = self._real_elems.get_value()
        pad = self._pad_elems.get_value()
        total = real + pad
        return round(pad / total, 4) if total else 0.0

    def _prefix_skip_ratio(self) -> float:
        seen = self._seen_elems.get_value()
        return round(self._skip_elems.get_value() / seen, 4) if seen \
            else 0.0

    def stats(self) -> dict:
        with self._cv:
            queued = len(self._q)
        return {
            "max_batch_size": self.max_batch_size,
            "max_delay_us": self.max_delay_us,
            "eager": self.eager,
            "batch_buckets": list(self.batch_buckets),
            "length_buckets": list(self.length_buckets),
            "queued": queued,
            "batches": self.n_batches.get_value(),
            "completed": self.n_completed.get_value(),
            "bypassed": self.n_bypassed.get_value(),
            "errors": self.n_errors.get_value(),
            "shed": self.shed.get_value(),
            "brownout": self.brownout,
            "brownout_shed": self.brownout_shed.get_value(),
            "lane_promotions": self.lane_promotions.get_value(),
            "avg_batch_size": round(self.batch_size_rec.get_value(), 2),
            "pad_waste_ratio": self._pad_waste(),
            "prefix_skip_ratio": self._prefix_skip_ratio(),
            "queue_delay_avg_us": round(self.queue_delay_rec.latency(), 1),
            "queue_delay_p99_us": round(
                self.queue_delay_rec.latency_percentile(0.99), 1),
            "exec_ema_ms": round(self._exec_ema_s * 1e3, 3),
        }
