"""The ``_cluster`` control service — wire-level overload with epoch
fencing (ISSUE 16) and model-deployment lifecycle (ISSUE 18).

PR 8's overload gradient was cluster-wide in POLICY but local in
MECHANISM: levels 2-4 acted through in-process ``ReplicaHandle``
components, so a remote-only fleet only ever felt level 1 (less
traffic forwarded).  This service is the wire half, modeled on bRPC's
multiplexed control traffic (baidu_std rides control and data on one
connection — PAPER.md L4): each router tick pushes

    SetFloor {epoch, level, router}   (tensorframe-framed)

to every remote replica.  The replica applies the level through the
SAME policy as the in-process path
(:func:`~brpc_tpu.serving.ladder.apply_level_to_components`) and the
reply carries its pressure report back — so one RPC per tick both
browns the fleet out together AND feeds the router's gradient the
remote pressures it could not see before.

EPOCH FENCING.  ``epoch`` is the fleet membership epoch, persisted in
the session WAL and bumped by every adopting router.  The service
latches the highest epoch it has seen and REFUSES (EREQUEST, "stale
epoch") any push carrying a lower one: a superseded router that is
still ticking — the classic split-brain after a router failover —
cannot drag the fleet's overload posture around.  A dropped push needs
no special handling: the router re-pushes every tick (chaos scenario
17 drives both paths via ``cluster.floor_push``).

MODEL PLANE (ISSUE 18).  The same connection carries the deployment
catalog both ways: every ``SetFloor``/``Report`` reply embeds the
replica's :class:`~brpc_tpu.serving.modelplane.ReplicaDeployments`
snapshot as one JSON str field (``deployments``), so the router's
catalog converges within one tick of any replica-side change, with no
extra RPC.  Lifecycle mutations arrive as ``Deploy`` / ``Undeploy`` /
``Drain`` pushes and are fenced by the SAME epoch latch as
``SetFloor`` — a superseded router can no more reshape the fleet's
model topology than its overload posture (chaos scenario 19 proves
both refusals).  Deploy here is CATALOG-level: it marks an
already-bound deployment's state/weight (warm/draining) or registers a
catalog-only row; binding an actual engine/store happens at replica
spin-up where the accelerator lives.
"""
from __future__ import annotations

import time
from typing import Optional

from brpc_tpu import errors, fault
from brpc_tpu.butil.lockprof import InstrumentedLock
from brpc_tpu.rpc.service import Service, method
from brpc_tpu.serving.ladder import apply_level_to_components
from brpc_tpu.serving.modelplane import (LOADING, WARM, publish_deployments)

CLUSTER_SERVICE = "_cluster"


class ClusterControlService(Service):
    """Replica-side half of the wire-level overload gradient (see
    module docstring).  Holds the same component references a local
    :class:`~brpc_tpu.serving.router.ReplicaHandle` would, and applies
    pushed levels through the shared policy."""

    NAME = CLUSTER_SERVICE

    def __init__(self, *, supervisor=None, batcher=None, engine=None,
                 store=None, clamp_new_tokens: int = 32,
                 evict_pages: Optional[int] = None, name: str = "",
                 deployments=None):
        from brpc_tpu.serving.router import ReplicaHandle
        self.name = name
        self.clamp_new_tokens = int(clamp_new_tokens)
        self.evict_pages = evict_pages
        # a loopback handle purely for its pressures() logic
        self._handle = ReplicaHandle(
            "0.0.0.0:0", name=name or "local", supervisor=supervisor,
            batcher=batcher, engine=engine, store=store)
        self.deployments = deployments
        self._mu = InstrumentedLock("cluster.control")
        self.epoch = 0
        self.level = 0
        self.router = ""
        self.applied = 0
        self.refusals = 0
        self.deploy_ops = 0
        self.deploy_refusals = 0
        self.last_push_t: Optional[float] = None

    def _publish_into(self, resp: dict) -> dict:
        """Ride the deployment catalog on a control reply (one inline
        str field — tensorframe caps these at 1MB, plenty for any
        realistic deployment count)."""
        if self.deployments is not None:
            field = publish_deployments(self.deployments)
            if field is not None:
                resp["deployments"] = field
        return resp

    def _fence(self, cntl, req, *, counter: str) -> Optional[int]:
        """Latch-or-refuse the push's epoch.  Returns the epoch when
        admitted, None after set_failed (stale)."""
        epoch = int((req or {}).get("epoch", 0))
        with self._mu:
            if epoch < self.epoch:
                setattr(self, counter, getattr(self, counter) + 1)
                cntl.set_failed(
                    errors.EREQUEST,
                    f"stale epoch {epoch} < {self.epoch}: push from a "
                    f"superseded router refused")
                return None
            self.epoch = epoch
        return epoch

    @method(request="tensorframe", response="tensorframe")
    def SetFloor(self, cntl, req):
        req = req or {}
        epoch = self._fence(cntl, req, counter="refusals")
        if epoch is None:
            return None
        level = int(req.get("level", 0))
        with self._mu:
            self.level = level
            self.router = str(req.get("router", ""))
            self.applied += 1
            self.last_push_t = time.monotonic()
        h = self._handle
        apply_level_to_components(
            level, supervisor=h.supervisor, batcher=h.batcher,
            engine=h.engine, store=h.store,
            clamp_new_tokens=self.clamp_new_tokens,
            evict_pages=self.evict_pages)
        resp = {"applied": True, "epoch": epoch, "level": level}
        for k, v in h.pressures().items():
            resp[k] = float(v)
        return self._publish_into(resp)

    @method(request="tensorframe", response="tensorframe")
    def Report(self, cntl, req):
        """Pressure report without a level change — for pollers that
        are not the fleet's router (no epoch check: reading is free)."""
        resp = {"epoch": self.epoch, "level": self.level}
        for k, v in self._handle.pressures().items():
            resp[k] = float(v)
        return self._publish_into(resp)

    # -- model lifecycle (ISSUE 18) -------------------------------------

    def _lifecycle(self, cntl, req, op: str):
        req = req or {}
        if self.deployments is None:
            cntl.set_failed(errors.EREQUEST,
                            "replica has no deployment table")
            return None
        model = str(req.get("model") or "")
        if not model:
            cntl.set_failed(errors.EREQUEST, 'missing "model"')
            return None
        if fault.ENABLED and fault.hit("cluster.deploy", op=op,
                                       model=model, name=self.name):
            cntl.set_failed(errors.EINTERNAL,
                            f"injected deploy fault ({op} {model})")
            return None
        epoch = self._fence(cntl, req, counter="deploy_refusals")
        if epoch is None:
            return None
        deps = self.deployments
        if op == "deploy":
            state = str(req.get("state") or "") or None
            weight = int(req.get("weight", 1))
            row = deps.get(model)
            if row is not None:
                # re-deploy of a bound model: refresh weight/state
                # (canary re-weighting, un-drain) on the live bindings
                deps.deploy(model, engine=row.get("engine"),
                            batcher=row.get("batcher"),
                            store=row.get("store"),
                            prefix_fetcher=row.get("prefix_fetcher"),
                            state=state or row.get("state", LOADING),
                            weight=weight)
            else:
                # catalog-only deployment: visible on the plane, no
                # bindings yet (spin-up binds the engine later)
                deps.deploy(model, state=state or LOADING, weight=weight)
            if state == WARM:
                deps.mark_warm(model)
        elif op == "drain":
            if not deps.drain(model):
                cntl.set_failed(errors.EREQUEST,
                                f"model {model!r} not deployed here")
                return None
        elif op == "undeploy":
            if not deps.undeploy(model):
                cntl.set_failed(errors.EREQUEST,
                                f"model {model!r} not deployed here")
                return None
        with self._mu:
            self.deploy_ops += 1
        return self._publish_into(
            {"applied": True, "epoch": epoch, "op": op, "model": model})

    @method(request="tensorframe", response="tensorframe")
    def Deploy(self, cntl, req):
        """Register/refresh a deployment on this replica (epoch-fenced;
        ``state`` may force ``warm``, ``weight`` re-weights a canary)."""
        return self._lifecycle(cntl, req, "deploy")

    @method(request="tensorframe", response="tensorframe")
    def Undeploy(self, cntl, req):
        """Remove a deployment (epoch-fenced).  In-flight sessions on
        it keep their bindings; new placements stop immediately."""
        return self._lifecycle(cntl, req, "undeploy")

    @method(request="tensorframe", response="tensorframe")
    def Drain(self, cntl, req):
        """Mark a deployment draining (epoch-fenced): finishes
        in-flight work, leaves the placement ring for new sessions."""
        return self._lifecycle(cntl, req, "drain")

    def stats(self) -> dict:
        with self._mu:
            out = {
                "epoch": self.epoch,
                "level": self.level,
                "router": self.router,
                "applied": self.applied,
                "refusals": self.refusals,
                "deploy_ops": self.deploy_ops,
                "deploy_refusals": self.deploy_refusals,
                "push_age_s": (round(time.monotonic() - self.last_push_t,
                                     3) if self.last_push_t else None),
            }
        if self.deployments is not None:
            out["deployments"] = self.deployments.snapshot()
        return out


def register_cluster_control(server, *, supervisor=None, batcher=None,
                             engine=None, store=None,
                             clamp_new_tokens: int = 32,
                             evict_pages: Optional[int] = None,
                             name: str = "",
                             deployments=None) -> ClusterControlService:
    """Expose this replica to the wire-level overload gradient (call
    before ``server.start()``)."""
    svc = ClusterControlService(
        supervisor=supervisor, batcher=batcher, engine=engine,
        store=store, clamp_new_tokens=clamp_new_tokens,
        evict_pages=evict_pages, name=name, deployments=deployments)
    server.add_service(svc)
    return svc
