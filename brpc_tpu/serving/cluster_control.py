"""The ``_cluster`` control service — wire-level overload with epoch
fencing (ISSUE 16).

PR 8's overload gradient was cluster-wide in POLICY but local in
MECHANISM: levels 2-4 acted through in-process ``ReplicaHandle``
components, so a remote-only fleet only ever felt level 1 (less
traffic forwarded).  This service is the wire half, modeled on bRPC's
multiplexed control traffic (baidu_std rides control and data on one
connection — PAPER.md L4): each router tick pushes

    SetFloor {epoch, level, router}   (tensorframe-framed)

to every remote replica.  The replica applies the level through the
SAME policy as the in-process path
(:func:`~brpc_tpu.serving.ladder.apply_level_to_components`) and the
reply carries its pressure report back — so one RPC per tick both
browns the fleet out together AND feeds the router's gradient the
remote pressures it could not see before.

EPOCH FENCING.  ``epoch`` is the fleet membership epoch, persisted in
the session WAL and bumped by every adopting router.  The service
latches the highest epoch it has seen and REFUSES (EREQUEST, "stale
epoch") any push carrying a lower one: a superseded router that is
still ticking — the classic split-brain after a router failover —
cannot drag the fleet's overload posture around.  A dropped push needs
no special handling: the router re-pushes every tick (chaos scenario
17 drives both paths via ``cluster.floor_push``).
"""
from __future__ import annotations

import time
from typing import Optional

from brpc_tpu import errors
from brpc_tpu.butil.lockprof import InstrumentedLock
from brpc_tpu.rpc.service import Service, method
from brpc_tpu.serving.ladder import apply_level_to_components

CLUSTER_SERVICE = "_cluster"


class ClusterControlService(Service):
    """Replica-side half of the wire-level overload gradient (see
    module docstring).  Holds the same component references a local
    :class:`~brpc_tpu.serving.router.ReplicaHandle` would, and applies
    pushed levels through the shared policy."""

    NAME = CLUSTER_SERVICE

    def __init__(self, *, supervisor=None, batcher=None, engine=None,
                 store=None, clamp_new_tokens: int = 32,
                 evict_pages: Optional[int] = None, name: str = ""):
        from brpc_tpu.serving.router import ReplicaHandle
        self.name = name
        self.clamp_new_tokens = int(clamp_new_tokens)
        self.evict_pages = evict_pages
        # a loopback handle purely for its pressures() logic
        self._handle = ReplicaHandle(
            "0.0.0.0:0", name=name or "local", supervisor=supervisor,
            batcher=batcher, engine=engine, store=store)
        self._mu = InstrumentedLock("cluster.control")
        self.epoch = 0
        self.level = 0
        self.router = ""
        self.applied = 0
        self.refusals = 0
        self.last_push_t: Optional[float] = None

    @method(request="tensorframe", response="tensorframe")
    def SetFloor(self, cntl, req):
        req = req or {}
        epoch = int(req.get("epoch", 0))
        level = int(req.get("level", 0))
        with self._mu:
            if epoch < self.epoch:
                self.refusals += 1
                cntl.set_failed(
                    errors.EREQUEST,
                    f"stale epoch {epoch} < {self.epoch}: floor push "
                    f"from a superseded router refused")
                return None
            self.epoch = epoch
            self.level = level
            self.router = str(req.get("router", ""))
            self.applied += 1
            self.last_push_t = time.monotonic()
        h = self._handle
        apply_level_to_components(
            level, supervisor=h.supervisor, batcher=h.batcher,
            engine=h.engine, store=h.store,
            clamp_new_tokens=self.clamp_new_tokens,
            evict_pages=self.evict_pages)
        resp = {"applied": True, "epoch": epoch, "level": level}
        for k, v in h.pressures().items():
            resp[k] = float(v)
        return resp

    @method(request="tensorframe", response="tensorframe")
    def Report(self, cntl, req):
        """Pressure report without a level change — for pollers that
        are not the fleet's router (no epoch check: reading is free)."""
        resp = {"epoch": self.epoch, "level": self.level}
        for k, v in self._handle.pressures().items():
            resp[k] = float(v)
        return resp

    def stats(self) -> dict:
        with self._mu:
            return {
                "epoch": self.epoch,
                "level": self.level,
                "router": self.router,
                "applied": self.applied,
                "refusals": self.refusals,
                "push_age_s": (round(time.monotonic() - self.last_push_t,
                                     3) if self.last_push_t else None),
            }


def register_cluster_control(server, *, supervisor=None, batcher=None,
                             engine=None, store=None,
                             clamp_new_tokens: int = 32,
                             evict_pages: Optional[int] = None,
                             name: str = "") -> ClusterControlService:
    """Expose this replica to the wire-level overload gradient (call
    before ``server.start()``)."""
    svc = ClusterControlService(
        supervisor=supervisor, batcher=batcher, engine=engine,
        store=store, clamp_new_tokens=clamp_new_tokens,
        evict_pages=evict_pages, name=name)
    server.add_service(svc)
    return svc
