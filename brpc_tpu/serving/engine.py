"""Continuous-batching autoregressive decode engine.

A fixed pool of decode slots steps together through ONE jitted step
function; requests join and leave the step loop mid-flight (continuous
batching — no waiting for the slowest member of a static batch), and
each generated token streams back to its caller per step.

KV-cache residency follows the `ici/block_pool.py` discipline: every
admitted request leases one HBM block for its slot's KV cache
(``pool.alloc``) and releases it at retirement (``block.free``) —
occupancy returns to baseline after drain, so the chaos suite can
leak-check the engine exactly like the transport.

The step function sees FIXED shapes — ``step_fn(tokens[num_slots],
positions[num_slots])`` — so the jit cache compiles once for the life
of the engine regardless of how requests churn through the slots.
Inactive slots carry zeros; their outputs are ignored.

Emission: ``emit(token)`` runs on the engine thread once per generated
token — hand it a ``Stream.write`` (rpc/stream.py credit window) for
TRPC callers or a ``ProgressiveAttachment.write`` for HTTP clients.
``on_done(err)`` fires exactly once per request, success or failure.
"""
from __future__ import annotations

import itertools
import re
import threading
from collections import deque
from typing import Callable, Optional, Sequence

import numpy as np

from brpc_tpu import errors, fault
from brpc_tpu.bvar import Adder, IntRecorder, PassiveStatus

_req_ids = itertools.count(1)


class _Request:
    __slots__ = ("req_id", "prompt", "max_new_tokens", "emit", "on_done",
                 "_done_fired", "_mu")

    def __init__(self, prompt: Sequence[int], max_new_tokens: int,
                 emit: Callable[[int], None],
                 on_done: Optional[Callable]):
        self.req_id = next(_req_ids)
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.emit = emit
        self.on_done = on_done
        self._done_fired = False
        self._mu = threading.Lock()

    def finish(self, err: Optional[errors.RpcError]) -> None:
        """Exactly-once terminal notification."""
        with self._mu:
            if self._done_fired:
                return
            self._done_fired = True
        if self.on_done is not None:
            try:
                self.on_done(err)
            except Exception:
                # an on_done bug must not kill the engine thread, but it
                # must leave a trace — a silently-lost terminal message
                # reads as a hung client with no server-side evidence
                import logging
                logging.getLogger(__name__).exception(
                    "engine on_done callback raised")


class _Slot:
    __slots__ = ("req", "block", "last_token", "position", "generated")

    def __init__(self, req: _Request, block):
        self.req = req
        self.block = block                    # leased KV-cache block
        self.last_token = req.prompt[-1] if req.prompt else 0
        self.position = len(req.prompt)
        self.generated = 0


class DecodeEngine:
    """Continuous-decode loop over a fixed slot pool."""

    def __init__(self, step_fn: Callable, *,
                 num_slots: int = 8,
                 kv_bytes_per_slot: int = 4096,
                 pool=None,
                 device=None,
                 eos_token: Optional[int] = None,
                 max_new_tokens_cap: int = 65536,
                 name: str = "engine"):
        if num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        self.step_fn = step_fn
        self.num_slots = int(num_slots)
        self.kv_bytes_per_slot = int(kv_bytes_per_slot)
        self.eos_token = eos_token
        # hard per-request ceiling: a hostile/buggy max_new_tokens must
        # not pin a decode slot effectively forever (the glue layers
        # pass client-supplied values straight through)
        self.max_new_tokens_cap = int(max_new_tokens_cap)
        self.name = name
        if pool is None:
            from brpc_tpu.ici.block_pool import get_block_pool
            pool = get_block_pool(device)
        self.pool = pool

        safe = re.sub(r"\W", "_", name)
        # record the EXACT names exposed here so close() hides only this
        # engine's variables — a prefix wildcard would also strip a
        # sibling component whose name merely starts with ours
        from brpc_tpu.bvar.variable import exposed_variables
        pre = set(exposed_variables(f"serving_{safe}*"))
        self.steps = Adder(f"serving_{safe}_steps")
        self.tokens_out = Adder(f"serving_{safe}_tokens")
        self.retired = Adder(f"serving_{safe}_retired")
        self.admit_errors = Adder(f"serving_{safe}_admit_errors")
        self.occupancy_rec = IntRecorder(f"serving_{safe}_occupancy")
        PassiveStatus(self.active_count).expose(
            f"serving_{safe}_active_slots")
        self._bvar_names = [n for n in exposed_variables(f"serving_{safe}*")
                            if n not in pre]

        self._cv = threading.Condition()
        self._slots: list[Optional[_Slot]] = [None] * self.num_slots
        self._waiters: deque[_Request] = deque()
        self._running = True
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"serving-engine-{safe}")
        self._thread.start()
        from brpc_tpu import serving as _serving
        _serving._register_engine(self)

    # ---- submission ----

    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               emit: Callable[[int], None],
               on_done: Optional[Callable] = None) -> int:
        """Queue a request; it is admitted into the step loop at the next
        step boundary with a free slot (in-flight requests are never
        restarted).  Returns the request id; terminal state arrives via
        ``on_done(err)`` exactly once."""
        req = _Request(prompt, min(int(max_new_tokens),
                                   self.max_new_tokens_cap),
                       emit, on_done)
        if req.max_new_tokens <= 0:
            req.finish(errors.RpcError(errors.EREQUEST,
                                       "max_new_tokens must be > 0"))
            return req.req_id
        with self._cv:
            if not self._running:
                closed = True
            else:
                closed = False
                self._waiters.append(req)
                self._cv.notify()
        if closed:
            req.finish(errors.RpcError(errors.ELOGOFF, "engine closed"))
        return req.req_id

    def _admit_locked(self) -> None:
        """Move waiters into free slots (called at step boundaries under
        the cv).  A failed KV lease completes THAT request with a
        definite error and leaves the loop healthy."""
        for i in range(self.num_slots):
            if self._slots[i] is not None or not self._waiters:
                continue
            req = self._waiters.popleft()
            try:
                if fault.ENABLED and fault.hit(
                        "serving.slot_alloc", name=self.name,
                        slot=i) is not None:
                    raise MemoryError("injected KV slot alloc failure")
                block = self.pool.alloc(self.kv_bytes_per_slot)
            except Exception as e:
                self.admit_errors.add(1)
                req.finish(errors.RpcError(
                    errors.ELIMIT,
                    f"KV slot lease failed: {type(e).__name__}: {e}"))
                continue
            self._slots[i] = _Slot(req, block)

    # ---- the step loop ----

    def _loop(self) -> None:
        import jax.numpy as jnp
        while True:
            with self._cv:
                if not self._running:
                    # close() retires in-flight slots (with ELOGOFF) after
                    # joining this thread — exit at the step boundary
                    return
                self._admit_locked()
                active = [(i, s) for i, s in enumerate(self._slots)
                          if s is not None]
                if not active:
                    self._cv.wait()
                    continue
            tok = np.zeros((self.num_slots,), np.int32)
            pos = np.zeros((self.num_slots,), np.int32)
            for i, s in active:
                tok[i] = s.last_token
                pos[i] = s.position
            try:
                out = np.asarray(
                    self.step_fn(jnp.asarray(tok), jnp.asarray(pos)))
            except Exception as e:
                # a broken step function must not wedge callers: retire
                # every active request with a definite error
                err = errors.RpcError(
                    errors.EINTERNAL,
                    f"decode step failed: {type(e).__name__}: {e}")
                with self._cv:
                    reqs = [self._release_slot_locked(i)
                            for i, s in active]
                for req in filter(None, reqs):
                    req.finish(err)
                continue
            self.steps.add(1)
            self.occupancy_rec.add(len(active))
            for i, s in active:
                nxt = int(out[i])
                s.last_token = nxt
                s.position += 1
                s.generated += 1
                self.tokens_out.add(1)
                try:
                    s.req.emit(nxt)
                except Exception as e:
                    self._retire(i, errors.RpcError(
                        errors.EINTERNAL,
                        f"emit failed: {type(e).__name__}: {e}"))
                    continue
                if s.generated >= s.req.max_new_tokens or \
                        (self.eos_token is not None
                         and nxt == self.eos_token):
                    self._retire(i, None)

    def _release_slot_locked(self, i: int):
        """Release slot i under the cv: free the KV block back to the
        pool exactly once and return the request for the CALLER to
        finish OUTSIDE the lock — on_done may do a blocking network
        write (stream credit window), and firing it under the cv would
        stall the step loop, submit(), stats() and the exposed
        active-slots bvar for the whole write."""
        s = self._slots[i]
        if s is None:
            return None
        self._slots[i] = None
        self.retired.add(1)
        try:
            s.block.free()
        except Exception:
            pass
        return s.req

    def _retire(self, i: int, err) -> None:
        with self._cv:
            req = self._release_slot_locked(i)
        if req is not None:
            req.finish(err)

    # ---- lifecycle / introspection ----

    def active_count(self) -> int:
        with self._cv:
            return sum(1 for s in self._slots if s is not None)

    def close(self, timeout_s: float = 10.0) -> None:
        """Stop the loop; in-flight and queued requests complete with
        ELOGOFF and every leased KV block returns to the pool."""
        with self._cv:
            self._running = False
            self._cv.notify_all()
        self._thread.join(timeout_s)
        err = errors.RpcError(errors.ELOGOFF, "engine closed")
        with self._cv:
            reqs = [self._release_slot_locked(i)
                    for i in range(self.num_slots)]
            waiters, self._waiters = list(self._waiters), deque()
        for req in filter(None, reqs):
            req.finish(err)
        for req in waiters:
            req.finish(err)
        # unpin exposed bvars (bound-method PassiveStatus would keep a
        # closed engine alive in the global registry forever)
        from brpc_tpu.bvar.variable import find_exposed
        for n in self._bvar_names:
            v = find_exposed(n)
            if v is not None:
                v.hide()

    def join_idle(self, timeout_s: float = 30.0) -> bool:
        """Block until no request is active or queued (drain helper for
        tests and graceful shutdown)."""
        import time
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._cv:
                if not self._waiters and all(
                        s is None for s in self._slots):
                    return True
            time.sleep(0.005)
        return False

    def stats(self) -> dict:
        with self._cv:
            slot_map = [
                None if s is None else {
                    "req_id": s.req.req_id,
                    "generated": s.generated,
                    "max_new_tokens": s.req.max_new_tokens,
                    "position": s.position,
                } for s in self._slots]
            queued = len(self._waiters)
        return {
            "num_slots": self.num_slots,
            "kv_bytes_per_slot": self.kv_bytes_per_slot,
            "slots": slot_map,
            "queued": queued,
            "steps": self.steps.get_value(),
            "tokens": self.tokens_out.get_value(),
            "retired": self.retired.get_value(),
            "admit_errors": self.admit_errors.get_value(),
            "avg_step_occupancy": round(self.occupancy_rec.get_value(), 2),
        }
