"""Continuous-batching autoregressive decode engine.

A fixed pool of decode slots steps together through ONE jitted step
function; requests join and leave the step loop mid-flight (continuous
batching — no waiting for the slowest member of a static batch), and
each generated token streams back to its caller per step.

KV-cache residency has two modes:

  * raw block leases (default, the PR 2 discipline): every admitted
    request leases one HBM block from `ici/block_pool.py`
    (``pool.alloc`` at admit, ``block.free`` at retire) — occupancy
    returns to baseline after drain, so the chaos suite can leak-check
    the engine exactly like the transport;
  * a paged KV cache (``store=`` a
    :class:`~brpc_tpu.kvcache.KVCacheStore`): admission goes through
    ``store.admit`` — the prompt's longest cached prefix is served by
    SHARED pages and only the suffix is prefilled (``prefill_fn``, if
    given, runs once per admit on the bucket-padded suffix, so the jit
    cache sees a handful of shapes however prompts vary); each
    generated token extends the sequence's page table (copy-on-write
    when a page is shared), and the step function — when it accepts a
    third argument — receives the gathered per-slot page tables as a
    fixed-shape int32 ``[num_slots, max_pages_per_slot]`` array (-1
    padded), compiled once for the life of the engine.

The step function sees FIXED shapes — ``step_fn(tokens[num_slots],
positions[num_slots])`` (+ optional page table) — so the jit cache
compiles once for the life of the engine regardless of how requests
churn through the slots.  Inactive slots carry zeros; their outputs
are ignored.

The MODEL surface is a :class:`~brpc_tpu.models.runner.ModelRunner`
(ISSUE 10): pass ``runner=`` for a real model — a
``TransformerRunner`` attends over THIS engine's gathered page tables
with the paged-attention kernel and returns packed K/V rows the step
loop splices back into the store's pages (``write_kv``), so prefix
reuse, COW forks and crash recovery operate on real attention state.
The legacy ``step_fn``/``prefill_fn`` protocols wrap in a
``LegacyFnRunner`` adapter with byte-identical behavior.

Emission: each admitted request gets a BOUNDED emit buffer drained by
its own emitter thread — the shared step loop never blocks in
``emit``.  A consumer that stops draining (stream credit exhausted,
dead HTTP peer) fills its buffer and is CUT with EOVERCROWDED at the
next step boundary while every other slot keeps streaming; a raising
``emit`` retires just that request.  ``on_done(err)`` fires exactly
once per request, success or failure, after its buffered tokens flush.

Supervision (serving/supervisor.py): the step loop publishes a
step-progress HEARTBEAT every iteration (suppressible by the
``serving.heartbeat`` fault site so a wedged loop can be simulated
deterministically).  With an ``on_crash`` handler installed, a step
failure — including the ``serving.step`` fault site — does NOT retire
the in-flight requests with errors: the loop stops with every slot
intact and the handler is told, so a supervisor can ``takeover()`` the
slots/waiters, re-attach their KV to the store, and re-admit them into
a replacement engine.  Unsupervised engines keep the PR 2 behavior (a
broken step function fails its requests definitively).
"""
from __future__ import annotations

import ctypes
import itertools
import re
import threading
import time
from collections import deque
from typing import Callable, Optional, Sequence

import numpy as np

from brpc_tpu import errors, fault, native_path, rpcz
from brpc_tpu.butil import hostcpu, stagetag
from brpc_tpu.butil.lockprof import InstrumentedLock
from brpc_tpu.bvar import Adder, IntRecorder, LatencyRecorder, PassiveStatus

_req_ids = itertools.count(1)

# Serving-wide latency recorders (ISSUE 5): TTFT (submit -> first token
# reaching the emit buffer), inter-token latency, and the per-stage
# breakdown (queue = submit -> slot install, prefill, decode = install
# -> retire).  LatencyRecorder exposes *_latency/_qps/_count and the
# percentile ladder, so /brpc_metrics scrapes them with no extra glue.
TTFT_REC = LatencyRecorder("serving_ttft_us")
ITL_REC = LatencyRecorder("serving_itl_us")
STAGE_QUEUE_REC = LatencyRecorder("serving_stage_queue_us")
STAGE_PREFILL_REC = LatencyRecorder("serving_stage_prefill_us")
STAGE_DECODE_REC = LatencyRecorder("serving_stage_decode_us")

# speculative decoding (ISSUE 11): serving-wide draft acceptance.  The
# ratio rides /brpc_metrics as one scrapeable gauge; per-generation
# acceptance is annotated on the decode spans and the generation ring.
SPEC_PROPOSED = Adder("serving_spec_proposed_tokens")
SPEC_ACCEPTED = Adder("serving_spec_accepted_tokens")


def _spec_accept_rate() -> float:
    p = SPEC_PROPOSED.get_value()
    return round(SPEC_ACCEPTED.get_value() / p, 4) if p else 0.0


PassiveStatus(_spec_accept_rate).expose("serving_spec_accept_rate")


class _EmitBuf:
    """Bounded token buffer between the shared step loop and one
    request's emitter thread.  ``push`` never blocks (the step loop
    must not stall on a slow consumer); the terminal marker is always
    accepted so a cut/finished request can flush and notify."""

    __slots__ = ("cap", "q", "cv", "terminal", "has_terminal")

    def __init__(self, cap: int):
        self.cap = cap
        self.q: deque = deque()
        # every request's emit buffer shares ONE ledger entry (ISSUE
        # 6): per-instance stats would churn native recorder slots,
        # and the actionable number is the class-wide step-loop-vs-
        # emitter contention anyway
        self.cv = threading.Condition(InstrumentedLock("serving.emit_buf"))
        self.terminal = None
        self.has_terminal = False

    def push(self, tok: int) -> bool:
        with self.cv:
            if len(self.q) >= self.cap:
                return False
            self.q.append(tok)
            self.cv.notify()
            return True

    def push_terminal(self, err) -> None:
        with self.cv:
            if not self.has_terminal:
                self.has_terminal = True
                self.terminal = err
            self.cv.notify()

    def pop(self, timeout_s: float):
        """Next item: ``("tok", t)``, ``("done", err)`` once drained,
        or None on timeout."""
        with self.cv:
            if not self.q and not self.has_terminal:
                self.cv.wait(timeout_s)
            if self.q:
                return ("tok", self.q.popleft())
            if self.has_terminal:
                return ("done", self.terminal)
            return None


class _NativeEmitBuf:
    """Native bounded emit ring (ISSUE 9) with the _EmitBuf protocol
    plus batch pop.  The step loop pushes through ONE GIL-released
    ``brpc_tokring_push_many`` call per step across all slots (the
    engine batches; ``push`` here is the single-slot/fallback entry),
    and the emitter drains MANY tokens per wakeup via ``pop_batch``
    instead of a Python lock round-trip per token.  Semantics are
    identical to _EmitBuf: push never blocks, a full ring means the
    consumer is cut with EOVERCROWDED, the terminal is always accepted
    and only surfaces after every buffered token."""

    __slots__ = ("ring", "cap", "popbuf")

    def __init__(self, ring, cap: int):
        self.ring = ring
        self.cap = cap
        # the emitter thread owns this scratch array (single consumer)
        self.popbuf = (ctypes.c_int32 * min(int(cap), 512))()

    @property
    def handle(self):
        return self.ring.handle

    def push(self, tok: int) -> bool:
        return self.ring.push(int(tok))

    def push_terminal(self, err) -> None:
        self.ring.push_terminal(err)

    def pop_batch(self, timeout_s: float):
        """(count, terminal_seen, err) — tokens land in ``popbuf``."""
        return self.ring.pop_many(self.popbuf, timeout_s)

    def pop(self, timeout_s: float):
        """Single-item _EmitBuf-protocol pop (compat path for callers
        that drain one token at a time)."""
        one = (ctypes.c_int32 * 1)()
        n, term, err = self.ring.pop_many(one, timeout_s)
        if n:
            return ("tok", int(one[0]))
        if term:
            return ("done", err)
        return None


def _make_emit_buf(cap: int):
    ring = native_path.token_ring(cap)
    if ring is not None:
        return _NativeEmitBuf(ring, cap)
    return _EmitBuf(cap)


class _Request:
    __slots__ = ("req_id", "prompt", "max_new_tokens", "emit", "on_done",
                 "buf", "t_submit", "trace", "speculative",
                 "_done_fired", "_mu")

    def __init__(self, prompt: Sequence[int], max_new_tokens: int,
                 emit: Callable[[int], None],
                 on_done: Optional[Callable], emit_buffer: int,
                 trace_ctx: Optional[tuple] = None,
                 speculative: bool = True):
        self.req_id = next(_req_ids)
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.emit = emit
        self.on_done = on_done
        # opt-out flag: a False request rides a speculative engine as
        # a plain (zero-draft) member of the verify batch
        self.speculative = bool(speculative)
        self.buf = _make_emit_buf(emit_buffer)
        self.t_submit = time.monotonic()
        # (trace_id, parent_span_id, sampled): captured at submit from
        # the caller's current span (the RPC ingress span when coming
        # through Serving.Generate) or handed down explicitly (the
        # supervisor's generation-attempt span) — the decode slot runs
        # on the engine thread where the contextvar does not follow
        self.trace = trace_ctx if trace_ctx is not None \
            else rpcz.current_trace_ctx()
        self._done_fired = False
        self._mu = threading.Lock()

    @property
    def done_fired(self) -> bool:
        return self._done_fired

    def finish(self, err: Optional[errors.RpcError]) -> None:
        """Exactly-once terminal notification."""
        with self._mu:
            if self._done_fired:
                return
            self._done_fired = True
        if self.on_done is not None:
            try:
                self.on_done(err)
            except Exception:
                # an on_done bug must not kill its thread, but it must
                # leave a trace — a silently-lost terminal message reads
                # as a hung client with no server-side evidence
                import logging
                logging.getLogger(__name__).exception(
                    "engine on_done callback raised")


class _Slot:
    __slots__ = ("req", "block", "seq", "last_token", "position",
                 "generated", "span", "t_install", "t_first_tok",
                 "last_tok_t", "itl_n", "itl_sum_s", "itl_max_s",
                 "steps_run", "spec_steps", "spec_proposed",
                 "spec_accepted")

    def __init__(self, req: _Request, block=None, seq=None,
                 span=rpcz.NULL_SPAN):
        self.req = req
        self.block = block                    # leased KV-cache block, or
        self.seq = seq                        # paged KVSeq (store mode)
        self.last_token = req.prompt[-1] if req.prompt else 0
        self.position = len(req.prompt)
        self.generated = 0
        self.span = span                      # per-slot decode span
        self.t_install = time.monotonic()
        self.t_first_tok = 0.0
        self.last_tok_t = 0.0
        self.itl_n = 0                        # inter-token gaps recorded
        self.itl_sum_s = 0.0
        self.itl_max_s = 0.0
        self.steps_run = 0                    # engine iterations ridden
        self.spec_steps = 0                   # verify iterations of those
        self.spec_proposed = 0                # draft tokens proposed
        self.spec_accepted = 0                # draft tokens accepted


class _SpecPlan:
    """One slot's draft lease for one verify iteration: the proposed
    branches, the side-branch forks holding their pages, and the row
    layout inside the fixed-shape verify batch."""

    __slots__ = ("slot", "base", "branches", "forks", "rows",
                 "speculated")

    def __init__(self, slot: _Slot):
        self.slot = slot
        self.base = slot.position       # len(seq.tokens) pre-draft
        self.branches: list = []        # token chains (branch 0 in-seq)
        self.forks: list = []           # KVSeq per side branch
        self.rows: list = []            # per branch: its local row idxs
        self.speculated = False         # branch 0 appended to the seq


class DecodeEngine:
    """Continuous-decode loop over a fixed slot pool."""

    def __init__(self, step_fn: Optional[Callable] = None, *,
                 runner=None,
                 num_slots: int = 8,
                 kv_bytes_per_slot: int = 4096,
                 pool=None,
                 device=None,
                 store=None,
                 prefill_fn: Optional[Callable] = None,
                 prefill_buckets: Sequence[int] = (16, 64, 256, 1024,
                                                   4096),
                 max_pages_per_slot: int = 64,
                 pass_page_table: Optional[bool] = None,
                 emit_buffer: int = 256,
                 eos_token: Optional[int] = None,
                 max_new_tokens_cap: int = 65536,
                 on_crash: Optional[Callable] = None,
                 draft_runner=None,
                 draft_len: int = 4,
                 name: str = "engine"):
        if num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        if emit_buffer < 1:
            raise ValueError("emit_buffer must be >= 1")
        self.num_slots = int(num_slots)
        self.kv_bytes_per_slot = int(kv_bytes_per_slot)
        self.eos_token = eos_token
        # hard per-request ceiling: a hostile/buggy max_new_tokens must
        # not pin a decode slot effectively forever (the glue layers
        # pass client-supplied values straight through)
        self.max_new_tokens_cap = int(max_new_tokens_cap)
        self.emit_buffer = int(emit_buffer)
        self.name = name
        # the paged KV cache is CALLER-owned (it outlives engines so the
        # radix tree keeps serving prefix hits across engine restarts);
        # close() never touches it
        self.store = store
        self.prefill_buckets = tuple(sorted(prefill_buckets))
        self.max_pages_per_slot = int(max_pages_per_slot)
        if pool is None and store is None:
            from brpc_tpu.ici.block_pool import get_block_pool
            pool = get_block_pool(device)
        self.pool = pool
        # the MODEL surface is a ModelRunner (ISSUE 10): legacy
        # 2-arg/3-arg step_fn / prefill_fn protocols wrap in a
        # LegacyFnRunner adapter with byte-identical behavior
        # (required-positional detection, pass_page_table override),
        # while a real runner (TransformerRunner) brings paged
        # attention over this engine's gathered page tables and packed
        # K/V rows the step loop splices back into the store's pages
        from brpc_tpu.models.runner import as_runner
        self.runner = as_runner(step_fn, prefill_fn, runner=runner,
                                store=store,
                                pass_page_table=pass_page_table)
        self._wants_pages = self.runner.wants_pages
        # vector-KV mode: the runner produces REAL packed K/V rows per
        # step; they must land in a store whose page slots carry that
        # exact layout
        self._vector_kv = self.runner.kv_bytes_per_token > 0
        if self._vector_kv:
            if store is None:
                raise ValueError("a vector-KV runner needs store= "
                                 "(its K/V live in the paged cache)")
            self.runner.bind(store)
        # speculative decoding (ISSUE 11): a draft proposer turns the
        # step loop into propose -> verify -> commit; the plain path is
        # byte-identical when no draft is configured
        from brpc_tpu.serving.speculative import as_proposer
        self._draft = as_proposer(draft_runner)
        self.draft_len = int(draft_len)
        if self._draft is not None:
            if store is None:
                raise ValueError("speculative decoding needs store= "
                                 "(draft leases live in the paged "
                                 "KV cache)")
            if self.draft_len < 1:
                raise ValueError("draft_len must be >= 1")

        safe = re.sub(r"\W", "_", name)
        # record the EXACT names exposed here so close() hides only this
        # engine's variables — a prefix wildcard would also strip a
        # sibling component whose name merely starts with ours
        from brpc_tpu.bvar.variable import exposed_variables
        pre = set(exposed_variables(f"serving_{safe}*"))
        self.steps = Adder(f"serving_{safe}_steps")
        self.tokens_out = Adder(f"serving_{safe}_tokens")
        self.retired = Adder(f"serving_{safe}_retired")
        self.admit_errors = Adder(f"serving_{safe}_admit_errors")
        self.emit_cut = Adder(f"serving_{safe}_emit_cut")
        self.occupancy_rec = IntRecorder(f"serving_{safe}_occupancy")
        PassiveStatus(self.active_count).expose(
            f"serving_{safe}_active_slots")
        self._bvar_names = [n for n in exposed_variables(f"serving_{safe}*")
                            if n not in pre]

        # supervision state: the crash handler is told (with every slot
        # left intact) instead of failing in-flight requests; the
        # heartbeat lets a watchdog distinguish a busy loop from a
        # wedged or dead one; degraded_clamp is the overload ladder's
        # max_new_tokens brownout, applied to NEW submissions only
        self._on_crash = on_crash
        self._crashed: Optional[BaseException] = None
        self._taken_over = False
        self.degraded_clamp: Optional[int] = None
        self._prefill_fn_cpu_s = 0.0   # model-fn CPU of the last admit
        self._beat_steps = 0
        self._beat_t = time.monotonic()

        # scratch for the per-step batched native emit push (ISSUE 9):
        # sized once at the slot count — times the per-slot burst in
        # speculative mode (accepted drafts + bonus land in ONE
        # GIL-released push_many, consecutive entries per ring) —
        # owned by the engine thread
        pushcap = self.num_slots * \
            (self.draft_len + 1 if self._draft is not None else 1)
        self._push_handles = (ctypes.c_void_p * pushcap)()
        self._push_toks = (ctypes.c_int32 * pushcap)()
        self._push_ok = (ctypes.c_uint8 * pushcap)()

        # the engine slot lock is a NAMED hot lock (ISSUE 6): submit,
        # the step loop, emitter cancels and the console all meet here
        self._cv = threading.Condition(InstrumentedLock("engine.slots"))
        self._slots: list[Optional[_Slot]] = [None] * self.num_slots
        self._waiters: deque[_Request] = deque()
        # requests popped from _waiters but not yet installed in a slot
        # (admission runs outside the cv): counted so join_idle()/
        # stats() never report idle while an admit is mid-flight
        self._admitting = 0
        self._running = True
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"serving-engine-{safe}")
        self._thread.start()
        from brpc_tpu import serving as _serving
        _serving._register_engine(self)

    # ---- submission ----

    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               emit: Callable[[int], None],
               on_done: Optional[Callable] = None, *,
               clamp: bool = True,
               trace_ctx: Optional[tuple] = None,
               speculative: bool = True) -> int:
        """Queue a request; it is admitted into the step loop at the next
        step boundary with a free slot (in-flight requests are never
        restarted).  Returns the request id; terminal state arrives via
        ``on_done(err)`` exactly once.  ``clamp=False`` exempts the
        submission from the overload ladder's ``degraded_clamp`` — the
        supervisor's crash re-admissions use it so a restart cannot
        silently truncate a budget the request was already admitted
        with.  ``trace_ctx=(trace_id, parent_span_id, sampled)``
        overrides the rpcz trace context captured from the calling
        thread (the supervisor passes its generation-attempt span so
        pre- and post-crash decode spans share one trace).
        ``speculative=False`` opts this request out of draft proposals
        on a speculative engine (it rides the verify batch as a plain
        zero-draft member; a no-draft engine ignores the flag)."""
        limit = self.max_new_tokens_cap
        brownout = self.degraded_clamp
        if clamp and brownout is not None:
            # overload-ladder brownout: new requests get shorter
            # generations so slots churn faster; in-flight requests
            # keep the budget they were admitted with
            limit = min(limit, int(brownout))
        req = _Request(prompt, min(int(max_new_tokens), limit),
                       emit, on_done, self.emit_buffer,
                       trace_ctx=trace_ctx, speculative=speculative)
        if req.max_new_tokens <= 0:
            req.finish(errors.RpcError(errors.EREQUEST,
                                       "max_new_tokens must be > 0"))
            return req.req_id
        if self.store is not None and not req.prompt:
            req.finish(errors.RpcError(errors.EREQUEST,
                                       "empty prompt (paged KV mode)"))
            return req.req_id
        with self._cv:
            if not self._running:
                closed = True
            else:
                closed = False
                self._waiters.append(req)
                self._cv.notify()
        if closed:
            req.finish(errors.RpcError(errors.ELOGOFF, "engine closed"))
        return req.req_id

    def _claim_waiters_locked(self) -> list:
        """Pop as many waiters as there are free slots (under the cv).
        Only the engine thread admits, so the free count can't shrink
        between the claim and the install — it can only grow if an
        emitter cancels a slot meanwhile."""
        free = sum(1 for s in self._slots if s is None)
        claimed = []
        while len(claimed) < free and self._waiters:
            claimed.append(self._waiters.popleft())
        self._admitting += len(claimed)
        return claimed

    def _admit(self, req: _Request):
        """Lease KV state for one claimed request OUTSIDE the cv — in
        store mode admit writes the whole prompt suffix to device, and
        holding the lock through that would stall submit()/stats() and
        the console exactly like an in-lock prefill would.  A failed
        lease completes THAT request with a definite error and leaves
        the loop healthy.  Returns the installed (index, slot) pair or
        None."""
        # per-slot decode span (ISSUE 5): child of the request's trace
        # (RPC ingress or supervisor attempt span); carries TTFT, ITL
        # and the KV-cache annotations for the whole slot residency.
        # NULL_SPAN when rpcz is off — every write below absorbs free.
        tid, psid, smp = req.trace
        span = rpcz.new_span("decode", "Serving", self.name,
                             trace_id=tid, parent_span_id=psid,
                             sampled=smp if tid else None)
        queue_us = int((time.monotonic() - req.t_submit) * 1e6)
        STAGE_QUEUE_REC.add(queue_us)
        seq = block = None
        try:
            if fault.ENABLED and fault.hit(
                    "serving.slot_alloc", name=self.name) is not None:
                raise MemoryError("injected KV slot alloc failure")
            if self.store is not None:
                # reject BEFORE admit writes anything: a prompt that
                # cannot fit the page table would otherwise burn device
                # splices (and evict healthy sequences' warm cache)
                # only to be rolled back — and installing it anyway
                # would silently truncate the gathered table and decode
                # on wrong KV
                need = -(-len(req.prompt) // self.store.page_tokens)
                if need > self.max_pages_per_slot:
                    raise MemoryError(
                        f"prompt needs {need} pages "
                        f"(> max_pages_per_slot="
                        f"{self.max_pages_per_slot})")
                seq = self.store.admit(req.prompt, span=span)
            else:
                block = self.pool.alloc(self.kv_bytes_per_slot)
        except Exception as e:
            if seq is not None:
                try:
                    self.store.retire(seq, cache=False)
                except Exception:
                    pass
            self.admit_errors.add(1)
            if span is not rpcz.NULL_SPAN:
                span.error_code = errors.ELIMIT
                span.annotate(f"kv admit failed: {type(e).__name__}: {e}")
                rpcz.submit(span)
            req.finish(errors.RpcError(
                errors.ELIMIT,
                f"KV admit failed: {type(e).__name__}: {e}"))
            return None
        if span is not rpcz.NULL_SPAN:
            span.annotate(f"slot install: queue_us={queue_us} "
                          f"prompt={len(req.prompt)} "
                          f"budget={req.max_new_tokens}")
        slot = _Slot(req, block=block, seq=seq, span=span)
        with self._cv:
            if self._running:
                for i in range(self.num_slots):
                    if self._slots[i] is None:
                        self._slots[i] = slot
                        return (i, slot)
        # the engine closed while we leased (close() already drained the
        # waiters deque, so nobody else will finish this request).
        # Under a TAKEOVER the prompt's pages are worth caching: the
        # supervisor will resubmit this exact prompt, and the committed
        # pages turn its re-admission into a prefix hit
        taken = self._taken_over
        try:
            if block is not None:
                block.free()
            if seq is not None:
                self.store.retire(seq, cache=taken)
        except Exception:
            pass
        if span is not rpcz.NULL_SPAN:
            span.error_code = errors.ELOGOFF
            span.annotate("engine closed mid-admit"
                          + (" (supervisor takeover)" if taken else ""))
            rpcz.submit(span)
        req.finish(errors.RpcError(
            errors.ELOGOFF,
            "engine restarting (supervisor takeover)" if taken
            else "engine closed"))
        return None

    # ---- emitter threads (one per admitted request) ----

    def _start_emitter(self, slot: _Slot) -> None:
        t = threading.Thread(target=self._emit_pump, args=(slot.req,),
                             daemon=True,
                             name=f"serving-emit-{slot.req.req_id}")
        t.start()

    def _emit_pump(self, req: _Request) -> None:
        """Drain one request's emit buffer.  Only THIS request stalls
        when its consumer blocks; emit failures retire just this
        request; the terminal marker flushes after the tokens and fires
        on_done exactly once."""
        if isinstance(req.buf, _NativeEmitBuf):
            return self._emit_pump_native(req)
        while True:
            item = req.buf.pop(0.25)
            if item is None:
                if req.done_fired:
                    return        # finished elsewhere (close timeout path)
                continue
            # emit fan-out host-CPU accounting (ISSUE 6): the pop wait
            # burns no thread_time, so measuring from here captures
            # exactly the per-token delivery work
            t_cpu0 = time.thread_time()
            kind, val = item
            if kind == "done":
                hostcpu.add("emit_fanout",
                            (time.thread_time() - t_cpu0) * 1e6)
                req.finish(val)
                return
            try:
                req.emit(val)
            except Exception as e:
                self._cancel(req, errors.RpcError(
                    errors.EINTERNAL,
                    f"emit failed: {type(e).__name__}: {e}"))
                return
            finally:
                hostcpu.add("emit_fanout",
                            (time.thread_time() - t_cpu0) * 1e6)

    def _emit_pump_native(self, req: _Request) -> None:
        """Native-ring emitter: each wakeup drains a BATCH of tokens in
        one GIL-released call (the pop wait parks in native code, off
        the GIL), then delivers them through the request's emit
        callback.  Terminal semantics are byte-for-byte the _EmitBuf
        pump's: every buffered token flushes before on_done fires
        exactly once."""
        buf: _NativeEmitBuf = req.buf
        out = buf.popbuf
        while True:
            n, term, err = buf.pop_batch(0.25)
            if n == 0 and not term:
                if req.done_fired:
                    return        # finished elsewhere (close timeout path)
                continue
            t_cpu0 = time.thread_time()
            try:
                for k in range(n):
                    req.emit(int(out[k]))
            except Exception as e:
                hostcpu.add("emit_fanout",
                            (time.thread_time() - t_cpu0) * 1e6)
                self._cancel(req, errors.RpcError(
                    errors.EINTERNAL,
                    f"emit failed: {type(e).__name__}: {e}"))
                return
            hostcpu.add("emit_fanout",
                        (time.thread_time() - t_cpu0) * 1e6)
            if term:
                req.finish(err)
                return

    def _cancel(self, req: _Request, err) -> None:
        """Retire `req`'s slot from OFF the engine thread (emitter saw
        its consumer die).  The engine thread may retire it first —
        exactly-once on finish makes the race benign."""
        released = None
        with self._cv:
            for i, s in enumerate(self._slots):
                if s is not None and s.req is req:
                    released = self._release_slot_locked(i, cache_ok=False)
                    break
        if released is not None:
            self._finalize_slot(released, err.code)
        req.finish(err)

    # ---- prefill (store mode) ----

    def _prefill(self, i: int, slot: _Slot) -> None:
        """Run the user prefill on the UNCACHED suffix of the prompt,
        bucket-padded so the jit cache compiles once per bucket.  The
        cached prefix — ``seq.prefix_hit_tokens`` tokens — is skipped
        entirely: that compute is what a cache hit buys.  A raising
        prefill retires the request (its emitter still drains the
        terminal)."""
        self._prefill_fn_cpu_s = 0.0
        if not self.runner.has_prefill or slot.seq is None:
            return
        suffix = slot.req.prompt[slot.seq.prefill_from:]
        if not suffix:
            return
        n = len(suffix)
        bucket = next((b for b in self.prefill_buckets if n <= b), n)
        padded = np.zeros((bucket,), np.int32)
        padded[:n] = suffix
        positions = slot.seq.prefill_from + np.arange(bucket,
                                                      dtype=np.int32)
        pages_row = np.full((self.max_pages_per_slot,), -1, np.int32)
        ids = slot.seq.page_ids()
        pages_row[:len(ids)] = ids[:self.max_pages_per_slot]
        # prefill child span: the cached/uncached split IS the story —
        # a cache hit is prefill compute skipped, and this span shows
        # exactly how much
        pspan = rpcz.NULL_SPAN
        if slot.span is not rpcz.NULL_SPAN:
            pspan = rpcz.new_span("prefill", "Serving", self.name,
                                  trace_id=slot.span.trace_id,
                                  parent_span_id=slot.span.span_id,
                                  sampled=slot.span.sampled)
            pspan.annotate(f"prefill: cached={slot.seq.prefill_from} "
                           f"uncached={n} bucket={bucket}")
        t0 = time.monotonic()
        t_fn_cpu = time.thread_time()
        try:
            self.runner.prefill(padded, positions, pages_row,
                                seq=slot.seq)
            self._prefill_fn_cpu_s = time.thread_time() - t_fn_cpu
        except Exception as e:
            self._prefill_fn_cpu_s = time.thread_time() - t_fn_cpu
            if pspan is not rpcz.NULL_SPAN:
                pspan.error_code = errors.EINTERNAL
                pspan.annotate(f"prefill failed: {type(e).__name__}: {e}")
                rpcz.submit(pspan)
            self._retire(i, errors.RpcError(
                errors.EINTERNAL,
                f"prefill failed: {type(e).__name__}: {e}"))
            return
        STAGE_PREFILL_REC.add(int((time.monotonic() - t0) * 1e6))
        rpcz.submit(pspan)

    # ---- the step loop ----

    def _touch_beat(self) -> None:
        """Publish step-loop progress for the supervisor's watchdog.
        The ``serving.heartbeat`` fault site SUPPRESSES the update —
        the loop keeps running but reports no progress, which is
        exactly what a wedged loop looks like from outside (so wedge
        detection and takeover-from-a-live-loop are deterministically
        testable without actually wedging a thread)."""
        if fault.ENABLED and fault.hit(
                "serving.heartbeat", name=self.name) is not None:
            return
        self._beat_steps += 1
        self._beat_t = time.monotonic()

    def heartbeat(self) -> tuple:
        """(progress counter, monotonic time of the last beat)."""
        return self._beat_steps, self._beat_t

    def has_work(self) -> bool:
        with self._cv:
            return (self._admitting > 0 or bool(self._waiters)
                    or any(s is not None for s in self._slots))

    def set_crash_handler(self, fn: Optional[Callable]) -> None:
        self._on_crash = fn

    @property
    def crashed(self) -> Optional[BaseException]:
        return self._crashed

    def _crash(self, exc: BaseException) -> None:
        """Supervised step failure: stop the loop with every slot
        INTACT (their requests are neither finished nor their KV
        leases released — the supervisor takes both over) and tell the
        crash handler.  Runs on the engine thread; the handler must
        only signal (the supervisor's watchdog does the heavy
        lifting)."""
        with self._cv:
            self._crashed = exc
            self._running = False
            self._cv.notify_all()
        try:
            self._on_crash(self, exc)
        except Exception:
            import logging
            logging.getLogger(__name__).exception(
                "engine crash handler raised")

    def _gather_page_tables(self, active) -> Optional[np.ndarray]:
        if not self._wants_pages:
            return None
        if native_path.enabled():
            # fixed-shape gather as one GIL-released native fill
            # (ISSUE 9); the row arrays stay referenced until the call
            # returns so their buffers cannot move
            table = np.empty((self.num_slots, self.max_pages_per_slot),
                             np.int32)
            rows = [(i, np.asarray(s.seq.page_ids(), np.int32))
                    for i, s in active if s.seq is not None]
            native_path.page_table_fill(
                table, [r for _, r in rows], [i for i, _ in rows])
            return table
        table = np.full((self.num_slots, self.max_pages_per_slot), -1,
                        np.int32)
        for i, s in active:
            if s.seq is None:
                continue
            ids = s.seq.page_ids()
            table[i, : len(ids)] = ids[: self.max_pages_per_slot]
        return table

    def _loop(self) -> None:
        while True:
            self._touch_beat()
            with self._cv:
                if not self._running:
                    # close() retires in-flight slots (with ELOGOFF) after
                    # joining this thread — exit at the step boundary
                    return
                claimed = self._claim_waiters_locked()
            # admission, prefill, and emitter start all run OUTSIDE the
            # cv: both are device calls and must not stall
            # submit()/stats() or the console
            for req in claimed:
                # stage override for the sampler (ISSUE 6): admission
                # device splices + prefill are prefill-side work even
                # though they run on the engine thread, whose NAME maps
                # to decode_step
                with stagetag.stage("prefill"):
                    t_cpu0 = time.thread_time()
                    installed = self._admit(req)
                    with self._cv:
                        self._admitting -= 1
                    if installed is None:
                        hostcpu.add("prefill",
                                    (time.thread_time() - t_cpu0) * 1e6)
                        continue
                    i, s = installed
                    self._prefill(i, s)
                    hostcpu.add("prefill",
                                (time.thread_time() - t_cpu0
                                 - self._prefill_fn_cpu_s) * 1e6)
                    hostcpu.add("model_compute",
                                self._prefill_fn_cpu_s * 1e6)
                self._start_emitter(s)
                # a long cold prefill is PROGRESS, not a wedge
                self._touch_beat()
            with self._cv:
                if not self._running:
                    return
                active = [(i, s) for i, s in enumerate(self._slots)
                          if s is not None]
                if not active:
                    if not self._waiters:
                        # bounded idle wait so the heartbeat keeps
                        # ticking: an idle-but-alive loop must stay
                        # distinguishable from a wedged one
                        self._cv.wait(0.25)
                    continue
            if self._draft is not None:
                if not self._spec_step(active):
                    return
            elif not self._plain_step(active):
                return

    def _plain_step(self, active) -> bool:
        """One plain decode iteration (the no-draft path, byte-for-byte
        the pre-ISSUE-11 loop body except that the per-slot KV row
        writes ride ONE ``write_kv_batch``).  Returns False when the
        loop must stop (supervised crash)."""
        t_cpu0 = time.thread_time()
        tok = np.zeros((self.num_slots,), np.int32)
        pos = np.zeros((self.num_slots,), np.int32)
        for i, s in active:
            tok[i] = s.last_token
            pos[i] = s.position
        pages = self._gather_page_tables(active)
        t_fn_cpu = time.thread_time()
        try:
            if fault.ENABLED and fault.hit(
                    "serving.step", name=self.name) is not None:
                raise RuntimeError("injected decode step crash")
            out, kv_rows = self.runner.step(tok, pos, pages)
        except Exception as e:
            if self._on_crash is not None:
                # supervised: this is an ENGINE failure, not the
                # requests' — leave every slot intact for takeover
                # and re-admission into the replacement engine
                self._crash(e)
                return False
            # unsupervised: a broken step function must not wedge
            # callers — retire every active request with a definite
            # error
            err = errors.RpcError(
                errors.EINTERNAL,
                f"decode step failed: {type(e).__name__}: {e}")
            with self._cv:
                released = [self._release_slot_locked(i,
                                                      cache_ok=False)
                            for i, s in active]
            for s in filter(None, released):
                self._finalize_slot(s, errors.EINTERNAL)
                s.req.buf.push_terminal(err)
            return True
        fn_cpu_s = time.thread_time() - t_fn_cpu
        self.steps.add(1)
        self.occupancy_rec.add(len(active))
        t_tok = time.monotonic()
        # the per-slot KV row writes ride ONE batched splice
        # (ISSUE 11): one H2D transfer + one I/O critical section
        # across every surviving slot instead of one per slot
        wrote_bad: set = set()
        if kv_rows is not None:
            items = [(i, s) for i, s in active
                     if self._slots[i] is s and s.seq is not None]
            fails = self.store.write_kv_batch(
                [(s.seq, s.position - 1, kv_rows[i:i + 1])
                 for i, s in items])
            for wi, e in fails:
                i, _ = items[wi]
                wrote_bad.add(i)
                self._retire(i, errors.RpcError(
                    errors.EINTERNAL,
                    f"KV write failed: {type(e).__name__}: {e}"))
        deliver: list = []   # (slot index, slot, token) surviving
        for i, s in active:
            if i in wrote_bad or self._slots[i] is not s:
                continue    # an emitter cancelled it mid-step
            nxt = int(out[i])
            s.last_token = nxt
            s.position += 1
            s.generated += 1
            s.steps_run += 1
            self.tokens_out.add(1)
            hostcpu.tokens_total.add(1)
            if s.last_tok_t:
                gap = t_tok - s.last_tok_t
                ITL_REC.add(int(gap * 1e6))
                s.itl_n += 1
                s.itl_sum_s += gap
                if gap > s.itl_max_s:
                    s.itl_max_s = gap
            else:
                s.t_first_tok = t_tok
                ttft_us = int((t_tok - s.req.t_submit) * 1e6)
                TTFT_REC.add(ttft_us)
                if s.span is not rpcz.NULL_SPAN:
                    s.span.annotate(f"first token: ttft_us={ttft_us}")
            s.last_tok_t = t_tok
            if s.seq is not None:
                try:
                    self.store.extend(s.seq, nxt)
                except MemoryError as e:
                    # pool exhausted and nothing evictable: THIS
                    # request errors, the loop and its peers go on
                    self._retire(i, errors.RpcError(
                        errors.ELIMIT,
                        f"KV page alloc failed: {e}"))
                    continue
                except Exception as e:
                    self._retire(i, errors.RpcError(
                        errors.EINTERNAL,
                        f"KV extend failed: {type(e).__name__}: {e}"))
                    continue
                if len(s.seq.pages) > self.max_pages_per_slot:
                    self._retire(i, errors.RpcError(
                        errors.ELIMIT,
                        f"page table overflow "
                        f"(> {self.max_pages_per_slot} pages)"))
                    continue
            deliver.append((i, s, nxt))
        # emit fan-out: ONE GIL-released native push across every
        # surviving slot's ring (ISSUE 9) — the per-token Python
        # lock acquire/notify this replaces was the step loop's
        # biggest fixed cost.  Python _EmitBuf requests (flag off /
        # no native lib / flipped mid-flight) push individually.
        # One-token runs of the speculative path's batched push — one
        # emit fan-out implementation for both loops.
        pushed = self._push_token_runs(
            [(i, s, (nxt,)) for i, s, nxt in deliver])
        for (i, s, nxt), ok in zip(deliver, pushed):
            if not ok:
                # consumer stopped draining: cut it HERE, without
                # the step loop ever blocking in a write
                self.emit_cut.add(1)
                if s.span is not rpcz.NULL_SPAN:
                    s.span.annotate(
                        f"emit-buffer stall: {self.emit_buffer} "
                        f"buffered tokens undrained, consumer cut")
                self._retire(i, errors.RpcError(
                    errors.EOVERCROWDED,
                    "slow stream consumer: emit buffer overflow"))
                continue
            if s.generated >= s.req.max_new_tokens or \
                    (self.eos_token is not None
                     and nxt == self.eos_token):
                self._retire(i, None)
        # per-stage host-CPU accounting (ISSUE 6): this iteration's
        # step-loop bookkeeping minus the model step itself
        hostcpu.add("decode_step",
                    (time.thread_time() - t_cpu0 - fn_cpu_s) * 1e6)
        hostcpu.add("model_compute", fn_cpu_s * 1e6)
        return True

    # ---- speculative decoding (ISSUE 11) ----

    def _spec_release(self, plan: "_SpecPlan") -> None:
        """Return one slot's draft lease to baseline: roll the main
        sequence back to its pre-draft length (unless something else —
        an emitter cancel's retire, a supervisor detach — already
        owns/released it) and retire every side-branch fork.  Runs on
        every non-commit exit path, so a crashed or cancelled verify
        can never leak a draft page."""
        s = plan.slot
        try:
            # unconditional: a speculate that raised MID-APPEND left a
            # partial draft tail the `speculated` flag never saw
            if s.seq is not None and not s.seq.retired \
                    and len(s.seq.tokens) > plan.base:
                self.store.rollback(s.seq, plan.base)
        except Exception:
            pass
        plan.speculated = False
        for f in plan.forks:
            if f is None:
                continue
            try:
                self.store.retire(f, cache=False)
            except Exception:
                pass
        plan.forks = []

    def _spec_propose(self, s: _Slot) -> list:
        """Draft branches for one slot, clamped to the row budget, the
        remaining token budget, and the fixed page-table width.  Empty
        when the slot opted out, has no headroom, or the proposer has
        nothing to say — the slot then rides the verify batch as a
        plain zero-draft member."""
        rem = s.req.max_new_tokens - s.generated
        if not s.req.speculative or rem <= 1 or s.seq is None:
            return []
        # the drafts (plus the bonus token) must fit the FIXED page
        # table the verify rows gather — never speculate past it
        avail = self.max_pages_per_slot * self.store.page_tokens \
            - s.position - 1
        cap = min(self.draft_len, rem - 1, avail)
        if cap < 1:
            return []
        try:
            branches = self._draft.propose(s.seq.tokens, cap)
        except Exception:
            return []      # a broken proposer degrades, never crashes
        kept, total = [], 0
        for b in branches:
            b = [int(t) for t in b][:cap - total]
            if not b:
                break
            kept.append(b)
            total += len(b)
        return kept

    def _spec_step(self, active) -> bool:
        """One speculative iteration: PROPOSE draft branches per slot,
        lease their pages (branch 0 rides the in-sequence draft cursor,
        side branches ride ``fork`` — COW isolates the divergent
        tails), VERIFY every row of every slot in ONE runner call, then
        COMMIT the longest greedy-matching prefix per slot: accepted
        rows' K/V splice in one ``write_kv_batch`` (page commit —
        ``kv_filled`` advances), rejected tails roll back (pages return
        to the pool), and the accepted tokens plus the target's bonus
        token fan out in one batched ring push.  Slots at different
        accept depths — including zero-draft plain slots — coexist in
        the one fixed-shape batch.  Returns False when the loop must
        stop (supervised crash)."""
        t_cpu0 = time.thread_time()
        k1 = self.draft_len + 1
        mp = self.max_pages_per_slot
        # ---- propose + lease ----
        plans: dict[int, _SpecPlan] = {}
        for i, s in active:
            plan = _SpecPlan(s)
            plans[i] = plan
            branches = self._spec_propose(s)
            if not branches:
                continue
            try:
                # forks FIRST (they must share only the base pages);
                # the branch-0 speculate then COWs the shared tail
                for b in branches[1:]:
                    f = self.store.fork(s.seq)
                    plan.forks.append(f)
                    self.store.speculate(f, b)
                self.store.speculate(s.seq, branches[0])
                plan.speculated = True
                plan.branches = branches
            except Exception:
                # lease pressure (pool exhausted mid-speculate):
                # degrade THIS slot to a plain step, peers keep their
                # drafts
                self._spec_release(plan)
                plan.branches = []
        if not any(p.branches for p in plans.values()):
            # nobody proposed (cold context the proposer has no basis
            # for, or every slot opted out): a (draft_len+1)-wide
            # verify would pay ~k1x the model FLOPs to emit one token
            # per slot — run the plain step instead.  No leases were
            # taken (empty branches lease nothing), and both paths
            # keep the same position/kv_filled invariants, so
            # iterations can alternate freely within one generation.
            return self._plain_step(active)
        # ---- build the fixed-shape verify batch ----
        tok = np.zeros((self.num_slots, k1), np.int32)
        pos = np.zeros((self.num_slots, k1), np.int32)
        tables = np.full((self.num_slots * k1, mp), -1, np.int32)
        base_len = np.zeros((self.num_slots * k1,), np.int32)
        mask = np.zeros((self.num_slots, k1, k1), bool)
        for i, s in active:
            plan = plans[i]
            base = s.position - 1          # materialized arena keys
            main_ids = np.full((mp,), -1, np.int32)
            ids = s.seq.page_ids() if s.seq is not None else []
            main_ids[:min(len(ids), mp)] = ids[:mp]
            tok[i, 0] = s.last_token
            pos[i, 0] = s.position
            mask[i, 0, 0] = True
            tables[i * k1] = main_ids
            base_len[i * k1] = base
            r = 1
            plan.rows = []
            for bi, b in enumerate(plan.branches):
                if bi == 0:
                    owner_ids = main_ids
                else:
                    owner_ids = np.full((mp,), -1, np.int32)
                    fids = plan.forks[bi - 1].page_ids()
                    owner_ids[:min(len(fids), mp)] = fids[:mp]
                rows = []
                for c, t in enumerate(b):
                    tok[i, r] = t
                    pos[i, r] = s.position + c + 1
                    tables[i * k1 + r] = owner_ids
                    base_len[i * k1 + r] = base
                    mask[i, r, 0] = True          # the shared root
                    for pr in rows:
                        mask[i, r, pr] = True     # branch ancestors
                    mask[i, r, r] = True          # self (in-call key)
                    rows.append(r)
                    r += 1
                plan.rows.append(rows)
        # ---- verify: the whole draft tree, one call ----
        t_fn_cpu = time.thread_time()
        try:
            if fault.ENABLED and fault.hit(
                    "serving.spec_verify", name=self.name) is not None:
                raise RuntimeError("injected speculative verify crash")
            out, kv_rows = self.runner.verify(tok, pos, tables,
                                              base_len, mask)
        except Exception as e:
            # draft leases FIRST — a crashed verify must leave zero
            # draft pages behind whether the supervisor takes over or
            # the requests fail definitively
            for plan in plans.values():
                self._spec_release(plan)
            if self._on_crash is not None:
                self._crash(e)
                return False
            err = errors.RpcError(
                errors.EINTERNAL,
                f"speculative verify failed: {type(e).__name__}: {e}")
            with self._cv:
                released = [self._release_slot_locked(i, cache_ok=False)
                            for i, s in active]
            for s in filter(None, released):
                self._finalize_slot(s, errors.EINTERNAL)
                s.req.buf.push_terminal(err)
            return True
        fn_cpu_s = time.thread_time() - t_fn_cpu
        self.steps.add(1)
        self.occupancy_rec.add(len(active))
        t_tok = time.monotonic()
        # ---- accept + commit ----
        writes: list = []         # (seq, pos, rows) for the batch splice
        write_owner: list = []    # slot index per staged write
        staged: dict[int, dict] = {}
        for i, s in active:
            plan = plans[i]
            if self._slots[i] is not s:
                # an emitter cancelled it mid-verify (its retire
                # already released the main lease); forks remain ours
                self._spec_release(plan)
                continue
            # greedy tree walk: the true next token at each row is the
            # target's argmax there; the winning branch is the longest
            # chain whose tokens match truth step by step
            t_star = int(out[i, 0])
            path: list = []
            winner = -1
            for bi, rows in enumerate(plan.rows):
                if not rows or int(tok[i, rows[0]]) != t_star:
                    continue
                sel = [rows[0]]
                for nxt_row in rows[1:]:
                    if int(tok[i, nxt_row]) == int(out[i, sel[-1]]):
                        sel.append(nxt_row)
                    else:
                        break
                if len(sel) > len(path):
                    path, winner = sel, bi
            a = len(path)
            bonus = int(out[i, path[-1]]) if path else t_star
            raw = [int(tok[i, r]) for r in path] + [bonus]
            if self.eos_token is not None and self.eos_token in raw:
                raw = raw[:raw.index(self.eos_token) + 1]
            rem = s.req.max_new_tokens - s.generated
            raw = raw[:rem]
            n = len(raw)
            kept = min(n, a)
            bonus_emitted = n == a + 1
            proposed = sum(len(b) for b in plan.branches)
            try:
                if winner > 0:
                    # a side branch won: the slot ADOPTS its fork (the
                    # fork owns base refs + the branch's draft pages);
                    # the original — and branch 0's draft tail with it
                    # — retires uncached
                    f = plan.forks[winner - 1]
                    plan.forks[winner - 1] = None
                    f.prefill_from = s.seq.prefill_from
                    f.span = s.seq.span
                    self.store.retire(s.seq, cache=False)
                    s.seq = f
                    plan.speculated = True   # fork tail rolls back below
                # reject: truncate to the accepted prefix, releasing
                # the rejected tail's pages
                self.store.rollback(s.seq, plan.base + kept)
                plan.speculated = False
                for f in plan.forks:
                    if f is not None:
                        self.store.retire(f, cache=False)
                plan.forks = []
                if kv_rows is None:
                    # token-harness pages: the stand-in bytes landed at
                    # speculate time — accepting IS the cursor advance
                    self.store.commit_draft(s.seq, plan.base + kept)
            except Exception as e:
                self._spec_release(plan)
                self._retire(i, errors.RpcError(
                    errors.EINTERNAL,
                    f"spec commit failed: {type(e).__name__}: {e}"))
                continue
            if kv_rows is not None:
                # accepted rows' REAL K/V (row 0 = the query position,
                # exactly the plain step's write) — staged for ONE
                # batched splice across all slots
                rows_sel = np.take(kv_rows[i], [0] + path[:kept],
                                   axis=0)
                writes.append((s.seq, plan.base - 1, rows_sel))
                write_owner.append(i)
            staged[i] = {"emit": raw, "kept": kept,
                         "bonus_emitted": bonus_emitted,
                         "proposed": proposed}
        fails = self.store.write_kv_batch(writes) if writes else []
        for wi, e in fails:
            i = write_owner[wi]
            staged.pop(i, None)
            self._retire(i, errors.RpcError(
                errors.EINTERNAL,
                f"KV write failed: {type(e).__name__}: {e}"))
        # ---- bookkeeping + emission ----
        deliver: list = []        # (slot index, slot, [tokens])
        for i, s in active:
            st = staged.get(i)
            if st is None:
                continue
            if self._slots[i] is not s:
                # an emitter CANCELLED the slot mid-commit: its release
                # retired whichever seq the slot held when it ran — if
                # that was before a side-branch adopt swapped s.seq,
                # the adopted fork is still ours to release.  A
                # supervisor TAKEOVER instead keeps the seq alive for
                # detach/re-admission.
                if not self._taken_over:
                    try:
                        if s.seq is not None and not s.seq.retired:
                            self.store.retire(s.seq, cache=False)
                    except Exception:
                        pass
                continue
            raw, kept = st["emit"], st["kept"]
            n = len(raw)
            if st["bonus_emitted"]:
                try:
                    self.store.extend(s.seq, raw[-1])
                except MemoryError as e:
                    self._retire(i, errors.RpcError(
                        errors.ELIMIT, f"KV page alloc failed: {e}"))
                    continue
                except Exception as e:
                    self._retire(i, errors.RpcError(
                        errors.EINTERNAL,
                        f"KV extend failed: {type(e).__name__}: {e}"))
                    continue
            if len(s.seq.pages) > self.max_pages_per_slot:
                self._retire(i, errors.RpcError(
                    errors.ELIMIT,
                    f"page table overflow "
                    f"(> {self.max_pages_per_slot} pages)"))
                continue
            s.last_token = raw[-1]
            s.position = len(s.seq.tokens)
            s.generated += n
            s.steps_run += 1
            s.spec_steps += 1
            s.spec_proposed += st["proposed"]
            s.spec_accepted += kept
            SPEC_PROPOSED.add(st["proposed"])
            SPEC_ACCEPTED.add(kept)
            self.tokens_out.add(n)
            hostcpu.tokens_total.add(n)
            if s.last_tok_t:
                # one inter-BURST gap per verify: tokens genuinely
                # arrive together, so per-token zeros would only bury
                # the real cadence
                gap = t_tok - s.last_tok_t
                ITL_REC.add(int(gap * 1e6))
                s.itl_n += 1
                s.itl_sum_s += gap
                if gap > s.itl_max_s:
                    s.itl_max_s = gap
            else:
                s.t_first_tok = t_tok
                ttft_us = int((t_tok - s.req.t_submit) * 1e6)
                TTFT_REC.add(ttft_us)
                if s.span is not rpcz.NULL_SPAN:
                    s.span.annotate(f"first token: ttft_us={ttft_us}")
            s.last_tok_t = t_tok
            deliver.append((i, s, raw))
        pushed = self._push_token_runs(deliver)
        for (i, s, raw), ok in zip(deliver, pushed):
            if not ok:
                self.emit_cut.add(1)
                if s.span is not rpcz.NULL_SPAN:
                    s.span.annotate(
                        f"emit-buffer stall: {self.emit_buffer} "
                        f"buffered tokens undrained, consumer cut")
                self._retire(i, errors.RpcError(
                    errors.EOVERCROWDED,
                    "slow stream consumer: emit buffer overflow"))
                continue
            if s.generated >= s.req.max_new_tokens or \
                    (self.eos_token is not None
                     and raw[-1] == self.eos_token):
                self._retire(i, None)
        hostcpu.add("decode_step",
                    (time.thread_time() - t_cpu0 - fn_cpu_s) * 1e6)
        hostcpu.add("model_compute", fn_cpu_s * 1e6)
        return True

    def _push_token_runs(self, deliver: list) -> list:
        """THE emit fan-out (ISSUE 9/11): each entry is ``(i, slot,
        [tokens])`` — one token per slot from the plain step, a verify
        burst from the speculative step.  Every native ring's run rides
        the one GIL-released ``push_many`` as consecutive (handle,
        token) pairs (the ring preserves call order), Python _EmitBufs
        push token by token.  An entry reads False when ANY of its
        tokens failed to land — the consumer is cut with EOVERCROWDED,
        so a partially-delivered burst only ever precedes an error
        terminal, never a silent gap in a healthy stream.  The slot
        objects in ``deliver`` hold their requests (and so the ring
        wrappers) alive across the native call — a racing emitter
        cancel can retire the slot but never free the ring under
        us."""
        if not deliver:
            return []
        ok = [True] * len(deliver)
        native = []               # flat (entry idx, token) pairs
        for k, (i, s, toks) in enumerate(deliver):
            buf = s.req.buf
            if isinstance(buf, _NativeEmitBuf):
                native.extend((k, t) for t in toks)
            else:
                for t in toks:
                    if not buf.push(t):
                        ok[k] = False
                        break
        if native:
            h, t = self._push_handles, self._push_toks
            for j, (k, tk) in enumerate(native):
                h[j] = deliver[k][1].req.buf.handle
                t[j] = tk
            native_path._core_lib().core.brpc_tokring_push_many(
                h, t, len(native), self._push_ok)
            for j, (k, _) in enumerate(native):
                if not self._push_ok[j]:
                    ok[k] = False
        return ok

    def _release_slot_locked(self, i: int, cache_ok: bool = True):
        """Release slot i under the cv: return the KV lease exactly once
        (raw block freed, or paged seq retired — cached into the radix
        tree only on clean completion) and return the SLOT for the
        CALLER to finalize (span/generation record) and finish (emit
        buffer's terminal marker) OUTSIDE the lock — collector handoff
        and the generation ring must not serialize the step loop."""
        s = self._slots[i]
        if s is None:
            return None
        self._slots[i] = None
        self.retired.add(1)
        try:
            if s.block is not None:
                s.block.free()
            if s.seq is not None:
                self.store.retire(s.seq, cache=cache_ok)
        except Exception:
            pass
        return s

    def _finalize_slot(self, s: _Slot, err_code: int) -> None:
        """Close out a retiring slot's observability state: the decode
        span (ITL summary annotation, error code) and one
        recent-generation record for the /serving/generations page."""
        now = time.monotonic()
        dur_us = int((now - s.t_install) * 1e6)
        STAGE_DECODE_REC.add(dur_us)
        ttft_us = int((s.t_first_tok - s.req.t_submit) * 1e6) \
            if s.t_first_tok else 0
        itl_avg_us = int(s.itl_sum_s / s.itl_n * 1e6) if s.itl_n else 0
        itl_max_us = int(s.itl_max_s * 1e6)
        # per-generation speculative-decoding summary (ISSUE 11):
        # acceptance and depth for the decode span and the
        # /serving/generations ring — the numbers that say whether the
        # draft is earning its keep for THIS traffic
        spec = None
        if self._draft is not None and s.spec_steps:
            spec = {
                "spec_proposed": s.spec_proposed,
                "spec_accepted": s.spec_accepted,
                "accept_rate": round(
                    s.spec_accepted / s.spec_proposed, 4)
                if s.spec_proposed else 0.0,
                "draft_depth": round(
                    s.spec_proposed / s.spec_steps, 2),
                # over ALL engine iterations, including the plain-step
                # fallbacks a cold context rides before drafts land —
                # the number that says what speculation bought the
                # whole generation
                "tokens_per_step": round(
                    s.generated / max(1, s.steps_run), 2),
            }
        span = s.span
        if span is not rpcz.NULL_SPAN:
            span.error_code = span.error_code or err_code
            span.annotate(
                f"retired: generated={s.generated} ttft_us={ttft_us} "
                f"itl_avg_us={itl_avg_us} itl_max_us={itl_max_us}")
            if spec is not None:
                span.annotate(
                    f"speculative: accept_rate={spec['accept_rate']} "
                    f"draft_depth={spec['draft_depth']} "
                    f"tokens_per_step={spec['tokens_per_step']} "
                    f"({spec['spec_accepted']}/{spec['spec_proposed']} "
                    f"drafts accepted over {s.spec_steps} verifies)")
            rpcz.submit(span)
        try:
            from brpc_tpu import serving as _serving
            _serving.record_generation({
                "engine": self.name,
                "req_id": s.req.req_id,
                "trace_id": span.trace_id,
                "prompt_len": len(s.req.prompt),
                "prefix_hit": s.seq.prefix_hit_tokens
                if s.seq is not None else 0,
                "generated": s.generated,
                "ttft_us": ttft_us,
                "itl_avg_us": itl_avg_us,
                "itl_max_us": itl_max_us,
                "duration_us": dur_us,
                "error_code": err_code,
                **(spec or {}),
            })
        except Exception:
            pass  # a console-ring bug must never break a retire

    def _retire(self, i: int, err) -> None:
        with self._cv:
            s = self._release_slot_locked(i, cache_ok=err is None)
        if s is not None:
            self._finalize_slot(s, err.code if err is not None else 0)
            s.req.buf.push_terminal(err)

    # ---- lifecycle / introspection ----

    def takeover(self) -> tuple:
        """Stop a crashed/wedged engine WITHOUT completing its
        requests: detach every in-flight slot and queued waiter so a
        supervisor can re-attach their KV to the store and re-admit
        them into a replacement engine.  Returns ``(slots, waiters)``
        — the caller now OWNS each slot's KV lease (block or seq) and
        each request's terminal notification.  Safe against a loop
        thread still stuck inside ``step_fn``: its post-step writes
        check slot identity, so a stolen slot's request can never
        receive another token from the old loop."""
        with self._cv:
            self._running = False
            self._taken_over = True
            self._cv.notify_all()
            stolen = [s for s in self._slots if s is not None]
            for i in range(self.num_slots):
                self._slots[i] = None
            waiters, self._waiters = list(self._waiters), deque()
        return stolen, waiters

    def active_count(self) -> int:
        with self._cv:
            return sum(1 for s in self._slots if s is not None)

    def close(self, timeout_s: float = 10.0) -> None:
        """Stop the loop; in-flight and queued requests complete with
        ELOGOFF and every KV lease (block or paged seq) returns to its
        pool.  The KV store itself is caller-owned and stays up."""
        with self._cv:
            self._running = False
            self._cv.notify_all()
        self._thread.join(timeout_s)
        err = errors.RpcError(errors.ELOGOFF, "engine closed")
        with self._cv:
            released = [self._release_slot_locked(i, cache_ok=False)
                        for i in range(self.num_slots)]
            waiters, self._waiters = list(self._waiters), deque()
        for s in filter(None, released):
            # the emitter drains buffered tokens then fires on_done;
            # finish() is exactly-once so a racing emitter is benign
            self._finalize_slot(s, errors.ELOGOFF)
            s.req.buf.push_terminal(err)
        for req in waiters:
            req.finish(err)   # never admitted: no emitter exists
        # unpin exposed bvars (bound-method PassiveStatus would keep a
        # closed engine alive in the global registry forever)
        from brpc_tpu.bvar.variable import find_exposed
        for n in self._bvar_names:
            v = find_exposed(n)
            if v is not None:
                v.hide()

    def join_idle(self, timeout_s: float = 30.0) -> bool:
        """Block until no request is active or queued (drain helper for
        tests and graceful shutdown)."""
        import time
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._cv:
                if not self._waiters and not self._admitting and all(
                        s is None for s in self._slots):
                    return True
            time.sleep(0.005)
        return False

    def queue_depth(self) -> float:
        """Admission backlog per slot (queued waiters + mid-admit over
        num_slots) — the queue-depth pressure the degradation ladders
        (supervisor and cluster router) escalate on."""
        with self._cv:
            queued = len(self._waiters) + self._admitting
        return queued / max(1, self.num_slots)

    def stats(self) -> dict:
        with self._cv:
            slot_map = [
                None if s is None else {
                    "req_id": s.req.req_id,
                    "generated": s.generated,
                    "max_new_tokens": s.req.max_new_tokens,
                    "position": s.position,
                    **({"pages": len(s.seq.pages),
                        "prefix_hit": s.seq.prefix_hit_tokens}
                       if s.seq is not None else {}),
                } for s in self._slots]
            queued = len(self._waiters) + self._admitting
        out = {
            "num_slots": self.num_slots,
            "kv_bytes_per_slot": self.kv_bytes_per_slot,
            "slots": slot_map,
            "queued": queued,
            "steps": self.steps.get_value(),
            "tokens": self.tokens_out.get_value(),
            "retired": self.retired.get_value(),
            "admit_errors": self.admit_errors.get_value(),
            "emit_buffer": self.emit_buffer,
            "emit_cut": self.emit_cut.get_value(),
            "avg_step_occupancy": round(self.occupancy_rec.get_value(), 2),
            "heartbeat_steps": self._beat_steps,
            "heartbeat_age_s": round(time.monotonic() - self._beat_t, 3),
            "crashed": self._crashed is not None,
            "degraded_clamp": self.degraded_clamp,
            "runner": self.runner.name,
            "vector_kv": self._vector_kv,
            "speculative": self._draft is not None,
        }
        if self._draft is not None:
            out["draft"] = getattr(self._draft, "name", "draft")
            out["draft_len"] = self.draft_len
        if self.store is not None:
            out["kvcache"] = self.store.name
        return out
