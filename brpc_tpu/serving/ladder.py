"""OverloadLadder — the shared escalation/hysteresis policy behind the
cluster's ONE overload gradient (ISSUE 8).

Before this module, overload was shed at four uncoordinated points:
the batcher's limiter, the supervisor's private degradation levels,
the engine's clamp, and the store's pressure eviction.  The ladder is
the policy those points now share: a list of per-level pressure
thresholds plus the escalate/de-escalate state machine the supervisor
grew in PR 4 —

  * ESCALATION IS IMMEDIATE: the moment any pressure metric crosses a
    level's threshold, the ladder jumps straight to that level (an
    overloaded system must not wait out a hysteresis window to start
    shedding);
  * DE-ESCALATION IS HYSTERETIC: one level at a time, and only after
    ``hysteresis_ticks`` consecutive calm ticks — a load oscillating
    around a threshold must not flap the ladder, because shedding
    churn is its own overload.

Both the :class:`~brpc_tpu.serving.supervisor.EngineSupervisor` (three
in-process levels: brownout / clamp / evict) and the
:class:`~brpc_tpu.serving.router.ClusterRouter` (four cluster levels:
shed-at-router / brownout-at-batcher / clamp-at-engine /
evict-at-store) consult a ladder instance, so the millions-of-users
story degrades along one coherent gradient — always shedding at the
cheapest layer first (a refused admission costs microseconds and no
DCN crossing; an evicted page costs a future recompute).

Each level keeps a fire counter (``escalations[level]``) so tests and
the ``/cluster`` console can PROVE the gradient ordering rather than
assert it from vibes.
"""
from __future__ import annotations

from typing import Mapping, Sequence


class OverloadLadder:
    """The escalate/hysteresis state machine over per-level pressure
    thresholds (see module docstring).

    ``thresholds`` is a sequence of dicts, one per level 1..N; a level
    is *pressed* when ANY of its metrics meets or exceeds its
    threshold.  ``update(pressures)`` advances the machine one tick
    and returns the (possibly unchanged) current level.  ``floor``
    lets an outer coordinator (the cluster router) hold a component at
    a minimum level regardless of its local pressures — the mechanism
    that makes the router's cluster-wide gradient coherent with each
    replica's local one.
    """

    def __init__(self, thresholds: Sequence[Mapping[str, float]], *,
                 hysteresis_ticks: int = 5,
                 level_names: Sequence[str] = ()):
        self.thresholds = tuple(dict(t) for t in thresholds)
        self.hysteresis_ticks = int(hysteresis_ticks)
        # optional display names, one per level (the training-plane
        # arbiter labels its background-tier rungs so /cluster and the
        # ordering proofs read "pace_trainer" instead of "level 1")
        self.level_names = tuple(level_names)
        if self.level_names and \
                len(self.level_names) != len(self.thresholds):
            raise ValueError("level_names must match thresholds")
        self.level = 0
        self.floor = 0
        self._calm_ticks = 0
        # fire counters per level (index 0 unused): incremented each
        # time an escalation first REACHES that level, so a ramp that
        # jumps 0 -> 3 counts levels 1, 2 and 3 — the gradient-order
        # proof reads these
        self.escalations = [0] * (len(self.thresholds) + 1)
        self.de_escalations = 0
        # tick of each level's FIRST fire (None = never): the
        # cheapest-first ordering proof is first_fired[cheap] <
        # first_fired[expensive] under a ramp, strict and readable
        self._tick = 0
        self.first_fired = [None] * (len(self.thresholds) + 1)

    @property
    def num_levels(self) -> int:
        return len(self.thresholds)

    def target_level(self, pressures: Mapping[str, float]) -> int:
        """The highest level whose threshold dict has ANY metric at or
        above its bound (0 when none are).  Metrics missing from
        ``pressures`` don't press."""
        lvl = 0
        for i, th in enumerate(self.thresholds, start=1):
            if any(k in pressures and pressures[k] >= th[k] for k in th):
                lvl = i
        return lvl

    def update(self, pressures: Mapping[str, float]) -> int:
        """One tick: escalate immediately to the pressed level,
        de-escalate one level per ``hysteresis_ticks`` calm ticks,
        never below ``floor``."""
        self._tick += 1
        target = max(self.target_level(pressures), self.floor)
        if target > self.level:
            for lvl in range(self.level + 1, target + 1):
                self.escalations[lvl] += 1
                if self.first_fired[lvl] is None:
                    self.first_fired[lvl] = self._tick
            self.level = target
            self._calm_ticks = 0
        elif target < self.level:
            self._calm_ticks += 1
            if self._calm_ticks >= self.hysteresis_ticks:
                self.level -= 1
                self.de_escalations += 1
                self._calm_ticks = 0
        else:
            self._calm_ticks = 0
        return self.level

    def reset(self) -> None:
        self.level = 0
        self.floor = 0
        self._calm_ticks = 0

    def stats(self) -> dict:
        return {
            "level": self.level,
            "floor": self.floor,
            "num_levels": self.num_levels,
            "hysteresis_ticks": self.hysteresis_ticks,
            "calm_ticks": self._calm_ticks,
            "escalations": list(self.escalations[1:]),
            "first_fired": list(self.first_fired[1:]),
            "level_names": list(self.level_names),
            "de_escalations": self.de_escalations,
        }


def apply_level_to_components(level: int, *, supervisor=None,
                              batcher=None, engine=None, store=None,
                              clamp_new_tokens: int = 32,
                              evict_pages=None) -> None:
    """Drive one replica's components to the cluster gradient `level`
    — the SHARED half of the router's four-level gradient, extracted
    (ISSUE 16) so the in-process path (``ClusterRouter._apply_level``
    over local handles) and the wire path (the ``_cluster`` control
    service applying a remote router's floor push) are literally the
    same policy:

      * a SUPERVISOR keeps its own ladder — it is held at a floor one
        below the cluster level (its 3 local levels sit under the
        router's shed level) and drives its own components;
      * otherwise: level >= 2 brownouts the batcher, >= 3 clamps new
        generations' budgets, >= 4 evicts pages each application.
    """
    if supervisor is not None:
        supervisor.set_level_floor(max(0, int(level) - 1))
        return
    if batcher is not None:
        batcher.brownout = max(batcher.brownout, 1) \
            if level >= 2 else 0
    if engine is not None:
        engine.degraded_clamp = clamp_new_tokens if level >= 3 else None
    if level >= 4 and store is not None:
        n = evict_pages
        if n is None:
            try:
                n = store.pagepool.pages_per_block
            except Exception:
                n = 4
        try:
            store.evict_pages(n)
        except Exception:
            pass
