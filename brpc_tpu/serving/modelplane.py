"""Multi-model plane — one durable front door fanning across named
model deployments (ISSUE 18 tentpole).

PR 16/17 made the cluster durable (session WAL, crash adoption, epoch
fencing) and mixed-workload (trainer behind the arbiter), but every
replica still served exactly ONE anonymous model.  This module is the
catalog layer that turns "a durable cluster" into "a durable service
catalog" — bRPC's many-services-behind-one-port motif lifted to model
deployments:

  * a DEPLOYMENT is a named ``model_id[@version]`` a replica serves:
    its engine/batcher/store bindings plus a lifecycle state —
    ``loading`` (bound, not yet proven by a generation), ``warm``
    (served at least one generation, or explicitly marked), and
    ``draining`` (finishes in-flight sessions but leaves the ring for
    NEW placements).  :class:`ReplicaDeployments` is the replica-side
    container; ``_cluster`` pressure replies publish its snapshot so
    the router needs no extra RPC to learn the fleet's catalog.

  * the router-side :class:`ModelCatalog` folds those publications
    (plus in-process handles) into "which replicas serve which model,
    in which state" — the admission and failover constraint set.

  * ROUTING is keyed by ``(model, prefix)``: :func:`model_fingerprint`
    folds the deployment key into the prefix fingerprint so two models
    sharing a token-identical system prompt land on DIFFERENT ring
    points and can never prefix-hit each other's pages.  The default
    (sole, anonymous) model keeps the plain prefix fingerprint, so a
    single-model fleet routes exactly as before this PR — the ≤5%
    overhead budget is structural, not incidental.

  * a CANARY split across versions of one ``model_id`` rides the
    ring's existing weighting: :class:`CanarySplit` is a smooth
    weighted round-robin over version weights (deterministic, so a
    95/5 target lands within the acceptance band under load), and
    :class:`ModelMetrics` keeps per-(model,version) TTFT/ITL/shed
    counters so a bad canary is visible on ``/cluster``.

Fault sites: ``router.model_route`` (the driver's model-constrained
pick is wrong — a stale-catalog mis-route; the driver counts it and
re-routes) and ``cluster.deploy`` (a deploy/undeploy/drain RPC lost or
refused on the wire) thread the plane into the chaos suite
(scenario 19).
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Optional, Sequence

from brpc_tpu.butil.lockprof import InstrumentedLock

# the sole anonymous deployment every pre-catalog fleet serves; old WAL
# records without a model column decode as this (version-tolerant
# recordio decode, regression-tested)
DEFAULT_MODEL = "default"

# deployment lifecycle states
LOADING = "loading"
WARM = "warm"
DRAINING = "draining"


def deployment_key(model_id: str, version: str = "") -> str:
    """The catalog key for one deployment: ``model_id`` alone, or
    ``model_id@version`` when versioned (the canary unit)."""
    model_id = str(model_id)
    return f"{model_id}@{version}" if version else model_id


def split_deployment_key(key: str) -> tuple[str, str]:
    """Inverse of :func:`deployment_key`: ``(model_id, version)`` with
    version ``""`` for unversioned keys."""
    key = str(key)
    if "@" in key:
        mid, _, ver = key.partition("@")
        return mid, ver
    return key, ""


def model_fingerprint(model: Optional[str], tokens: Sequence[int],
                      chunk_tokens: int = 16) -> int:
    """The ``(model, prefix)`` routing key: the prefix fingerprint with
    the deployment key folded in, so token-identical prompts against
    different models take DIFFERENT ring walks (and different ownership
    directory entries — zero cross-model page splices by construction).
    The default model keeps the plain prefix fingerprint: a
    single-model fleet's placement is bit-identical to pre-catalog
    routing."""
    from brpc_tpu.policy.load_balancer import (_hash_murmur_like,
                                               prefix_fingerprint)
    fp = prefix_fingerprint(tokens, chunk_tokens)
    if not model or model == DEFAULT_MODEL:
        return fp
    return _hash_murmur_like(
        str(model).encode() + b"\x00" +
        (fp & 0xFFFFFFFFFFFFFFFF).to_bytes(8, "little"))


class ReplicaDeployments:
    """Replica-side deployment container: the ``model key ->
    (bindings, lifecycle state)`` map one serving process holds.
    Published (as :meth:`snapshot`) on every ``_cluster`` pressure
    reply; consumed by :meth:`ServingService._resolve
    <brpc_tpu.serving.service.ServingService>` to route a forwarded
    ``model`` field to the right engine."""

    def __init__(self, name: str = ""):
        self.name = str(name)
        self._mu = InstrumentedLock("modelplane.replica")
        self._deps: dict[str, dict] = {}

    def deploy(self, model: str, *, engine=None, batcher=None,
               store=None, prefix_fetcher=None, state: str = LOADING,
               weight: int = 1) -> dict:
        """Bind (or re-bind) one deployment.  ``state`` starts
        ``loading`` unless the caller knows better; the first completed
        generation flips it warm (:meth:`note_generation`)."""
        if state not in (LOADING, WARM, DRAINING):
            raise ValueError(f"bad deployment state {state!r}")
        model = str(model)
        mid, ver = split_deployment_key(model)
        with self._mu:
            row = self._deps.get(model)
            if row is None:
                row = {"model": model, "model_id": mid, "version": ver,
                       "state": state, "weight": max(1, int(weight)),
                       "generations": 0,
                       "engine": engine, "batcher": batcher,
                       "store": store,
                       "prefix_fetcher": prefix_fetcher}
                self._deps[model] = row
            else:
                # re-deploy refreshes bindings/weight and RESETS a
                # draining deployment to the requested state
                row["state"] = state
                row["weight"] = max(1, int(weight))
                for k, v in (("engine", engine), ("batcher", batcher),
                             ("store", store),
                             ("prefix_fetcher", prefix_fetcher)):
                    if v is not None:
                        row[k] = v
            return dict(row)

    def mark_warm(self, model: str) -> bool:
        with self._mu:
            row = self._deps.get(str(model))
            if row is None or row["state"] == DRAINING:
                return False
            row["state"] = WARM
            return True

    def note_generation(self, model: str) -> None:
        """One generation completed against this deployment — the
        warm-up proof: a ``loading`` deployment flips ``warm``."""
        with self._mu:
            row = self._deps.get(str(model))
            if row is None:
                return
            row["generations"] += 1
            if row["state"] == LOADING:
                row["state"] = WARM

    def drain(self, model: str) -> bool:
        """Start draining: in-flight sessions finish (the bindings stay
        resolvable) but the published state removes this replica from
        NEW placements."""
        with self._mu:
            row = self._deps.get(str(model))
            if row is None:
                return False
            row["state"] = DRAINING
            return True

    def undeploy(self, model: str) -> bool:
        with self._mu:
            return self._deps.pop(str(model), None) is not None

    def get(self, model: str) -> Optional[dict]:
        with self._mu:
            row = self._deps.get(str(model))
            return dict(row) if row is not None else None

    def resolve(self, model: Optional[str]) -> tuple[str, dict]:
        """The binding a request for ``model`` should run on.  ``None``
        (a model-less request) resolves to the sole deployment, or the
        default one when several are bound.  Raises ``KeyError`` on an
        unknown model or an unresolvable model-less request — the
        caller's misroute/EREQUEST path."""
        with self._mu:
            if model:
                row = self._deps.get(str(model))
                if row is None:
                    raise KeyError(f"unknown model {model!r}")
                return str(model), row
            if len(self._deps) == 1:
                k = next(iter(self._deps))
                return k, self._deps[k]
            row = self._deps.get(DEFAULT_MODEL)
            if row is not None:
                return DEFAULT_MODEL, row
            raise KeyError(
                f"model-less request but {len(self._deps)} deployments "
                f"bound and none is {DEFAULT_MODEL!r}")

    def keys(self) -> list[str]:
        with self._mu:
            return sorted(self._deps)

    def __len__(self) -> int:
        with self._mu:
            return len(self._deps)

    def snapshot(self) -> list[dict]:
        """The publication rows (no binding objects — wire-safe)."""
        with self._mu:
            return [{"model": r["model"], "model_id": r["model_id"],
                     "version": r["version"], "state": r["state"],
                     "weight": r["weight"],
                     "generations": r["generations"]}
                    for r in self._deps.values()]


class ModelCatalog:
    """Router-side view of the fleet's deployments: ``replica addr ->
    {model key -> publication row}``, folded from in-process
    :class:`ReplicaDeployments` handles and from the ``deployments``
    field remote ``_cluster`` replies carry.  Everything the admission
    path (resolve/canary) and the failover path (same-model constraint)
    need is answered here without an RPC."""

    def __init__(self):
        self._mu = InstrumentedLock("modelplane.catalog")
        self._by_addr: dict[str, dict[str, dict]] = {}

    def note(self, addr: str, rows: Sequence[dict]) -> None:
        """Fold one replica's publication (full-state: rows REPLACE the
        replica's previous entry, so an undeploy is visible as
        absence)."""
        parsed = {}
        for r in rows or ():
            try:
                key = str(r["model"])
            except (TypeError, KeyError):
                continue
            mid, ver = split_deployment_key(key)
            parsed[key] = {
                "model": key,
                "model_id": str(r.get("model_id") or mid),
                "version": str(r.get("version") or ver),
                "state": str(r.get("state") or WARM),
                "weight": max(1, int(r.get("weight") or 1)),
                "generations": int(r.get("generations") or 0)}
        with self._mu:
            self._by_addr[str(addr)] = parsed

    def forget(self, addr: str) -> None:
        with self._mu:
            self._by_addr.pop(str(addr), None)

    def empty(self) -> bool:
        with self._mu:
            return not any(self._by_addr.values())

    def keys(self) -> list[str]:
        with self._mu:
            out = set()
            for deps in self._by_addr.values():
                out.update(deps)
            return sorted(out)

    def has(self, model: str) -> bool:
        model = str(model)
        with self._mu:
            return any(model in deps for deps in self._by_addr.values())

    def replicas_for(self, model: str, *,
                     for_new: bool = True) -> list[str]:
        """Replicas serving ``model``: warm first, then loading.  With
        ``for_new`` (placements for new/failed-over work) draining
        replicas are excluded — they only finish what they already
        hold."""
        model = str(model)
        warm, loading, draining = [], [], []
        with self._mu:
            for addr, deps in self._by_addr.items():
                row = deps.get(model)
                if row is None:
                    continue
                {WARM: warm, LOADING: loading,
                 DRAINING: draining}.get(row["state"], loading).append(addr)
        out = warm + loading
        if not for_new:
            out += draining
        return out

    def resolve(self, model: str) -> list[str]:
        """Deployment KEYS matching ``model``: the exact key when one
        exists (an explicitly versioned request is never widened), else
        every versioned key of the bare ``model_id`` (the canary set).
        Empty for an unknown model."""
        model = str(model)
        with self._mu:
            exact = any(model in deps for deps in self._by_addr.values())
            if exact:
                return [model]
            keys = set()
            for deps in self._by_addr.values():
                for key, row in deps.items():
                    if row["model_id"] == model:
                        keys.add(key)
        return sorted(keys)

    def version_weights(self, model_id: str) -> dict[str, int]:
        """Canary weights per deployment key of ``model_id`` — the MAX
        published weight across replicas (weights are a property of the
        version, not the replica)."""
        model_id = str(model_id)
        out: dict[str, int] = {}
        with self._mu:
            for deps in self._by_addr.values():
                for key, row in deps.items():
                    if row["model_id"] == model_id \
                            and row["state"] != DRAINING:
                        out[key] = max(out.get(key, 0), row["weight"])
        return out

    def sole_key(self) -> Optional[str]:
        ks = self.keys()
        return ks[0] if len(ks) == 1 else None

    def snapshot(self) -> dict[str, list[dict]]:
        with self._mu:
            return {addr: [dict(r) for r in deps.values()]
                    for addr, deps in self._by_addr.items()}


class CanarySplit:
    """Deterministic smooth weighted round-robin across the versions of
    one ``model_id`` — nginx's smooth-WRR, the same behavior class as
    ``policy/weighted_round_robin``: over any window of N picks each
    version receives ``N * w_i / sum(w)`` ± 1, so a 95/5 target lands
    within the acceptance band without randomness."""

    def __init__(self):
        self._mu = InstrumentedLock("modelplane.canary")
        self._cur: dict[str, dict[str, int]] = {}    # model_id -> key -> current
        self._picks: dict[str, dict[str, int]] = {}  # model_id -> key -> count

    def pick(self, model_id: str, weights: dict[str, int]) -> str:
        if not weights:
            raise ValueError(f"no versions to pick for {model_id!r}")
        model_id = str(model_id)
        with self._mu:
            cur = self._cur.setdefault(model_id, {})
            # drop versions that disappeared (undeployed canary)
            for k in list(cur):
                if k not in weights:
                    del cur[k]
            total = 0
            for k, w in weights.items():
                w = max(1, int(w))
                cur[k] = cur.get(k, 0) + w
                total += w
            best = max(sorted(cur), key=lambda k: cur[k])
            cur[best] -= total
            picks = self._picks.setdefault(model_id, {})
            picks[best] = picks.get(best, 0) + 1
            return best

    def snapshot(self) -> dict:
        with self._mu:
            return {m: dict(p) for m, p in self._picks.items()}


class ModelMetrics:
    """Per-deployment-key serving counters — the canary's scoreboard:
    sessions/sheds/finishes plus bounded TTFT and inter-token-latency
    reservoirs (percentiles computed at snapshot; the rings are small
    enough that /cluster can render them every poll)."""

    RESERVOIR = 512

    def __init__(self):
        self._mu = InstrumentedLock("modelplane.metrics")
        self._rows: dict[str, dict] = {}

    def _row(self, model: str) -> dict:
        r = self._rows.get(model)
        if r is None:
            r = {"sessions": 0, "sheds": 0, "finished": 0, "failed": 0,
                 "ttft_s": deque(maxlen=self.RESERVOIR),
                 "itl_s": deque(maxlen=self.RESERVOIR)}
            self._rows[model] = r
        return r

    def note_open(self, model: str) -> None:
        with self._mu:
            self._row(str(model))["sessions"] += 1

    def note_shed(self, model: str) -> None:
        with self._mu:
            self._row(str(model))["sheds"] += 1

    def note_ttft(self, model: str, seconds: float) -> None:
        with self._mu:
            self._row(str(model))["ttft_s"].append(float(seconds))

    def note_itl(self, model: str, seconds: float) -> None:
        with self._mu:
            self._row(str(model))["itl_s"].append(float(seconds))

    def note_finish(self, model: str, error_code: int = 0) -> None:
        with self._mu:
            r = self._row(str(model))
            r["failed" if error_code else "finished"] += 1

    @staticmethod
    def _pcts(xs) -> dict:
        if not xs:
            return {"n": 0, "p50_ms": None, "p99_ms": None}
        s = sorted(xs)
        n = len(s)
        return {"n": n,
                "p50_ms": round(s[min(n - 1, int(0.50 * n))] * 1e3, 3),
                "p99_ms": round(s[min(n - 1, int(0.99 * n))] * 1e3, 3)}

    def snapshot(self) -> dict:
        with self._mu:
            out = {}
            for m, r in self._rows.items():
                out[m] = {"sessions": r["sessions"],
                          "sheds": r["sheds"],
                          "finished": r["finished"],
                          "failed": r["failed"],
                          "ttft": self._pcts(r["ttft_s"]),
                          "itl": self._pcts(r["itl_s"])}
            return out


def publish_deployments(deps: Optional[ReplicaDeployments]) -> Optional[str]:
    """The ``deployments`` field a ``_cluster`` reply carries: the
    snapshot as one inline JSON string (tensorframe str fields cap at
    1 MiB — thousands of deployments before it matters)."""
    if deps is None:
        return None
    return json.dumps(deps.snapshot(), separators=(",", ":"))


def parse_deployments(field) -> Optional[list[dict]]:
    """Decode a ``deployments`` reply field; ``None`` on absence or any
    malformed payload (an old replica's reply simply lacks it)."""
    if not field:
        return None
    try:
        rows = json.loads(field)
    except (TypeError, ValueError):
        return None
    return rows if isinstance(rows, list) else None


def cluster_deploy(addr: str, *, epoch: int, model: str,
                   op: str = "deploy", weight: int = 1,
                   state: Optional[str] = None,
                   timeout_ms: int = 2_000) -> dict:
    """Push one lifecycle RPC (``deploy``/``undeploy``/``drain``) to a
    replica's ``_cluster`` service.  Carries the caller's membership
    epoch — a stale epoch is REFUSED exactly like a stale floor push
    (the superseded-router fence covers the catalog too).  Raises
    RpcError on refusal or transport failure."""
    from brpc_tpu.rpc.channel import Channel
    method = {"deploy": "Deploy", "undeploy": "Undeploy",
              "drain": "Drain"}.get(op)
    if method is None:
        raise ValueError(f"unknown deploy op {op!r}")
    req = {"epoch": int(epoch), "model": str(model)}
    if op == "deploy":
        req["weight"] = max(1, int(weight))
        if state is not None:
            req["state"] = str(state)
    ch = Channel(str(addr), timeout_ms=int(timeout_ms))
    return ch.call_sync("_cluster", method, req,
                        serializer="tensorframe",
                        response_serializer="tensorframe")


__all__ = [
    "DEFAULT_MODEL", "LOADING", "WARM", "DRAINING",
    "deployment_key", "split_deployment_key", "model_fingerprint",
    "ReplicaDeployments", "ModelCatalog", "CanarySplit", "ModelMetrics",
    "publish_deployments", "parse_deployments", "cluster_deploy",
]
