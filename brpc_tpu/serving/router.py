"""Cluster front door — resumable client sessions and one coherent
overload gradient across N serving replicas (ISSUE 8 tentpole).

The blueprint's client machinery (combo channels, circuit breakers,
health checks, quarantine) and the serving stack's data-plane
robustness (PR 4 in-process failover, PR 7 cross-process page
migration) existed side by side with nothing composing them: a client
talking to a dead replica still lost its generation, and overload was
shed at four uncoordinated points.  The :class:`ClusterRouter` is that
composition — assembled from the EXISTING pieces, not new transport:

ROUTING.  A :class:`~brpc_tpu.rpc.combo_channels.SelectiveChannel`
over one ``Channel`` per replica, its selection delegated to a
:class:`~brpc_tpu.policy.load_balancer.PrefixAffinityLB`: prompts
route by prefix fingerprint to the replica whose radix tree holds
their pages, health-check-broken and quarantined replicas are walked
past on the ring (remapping ONLY their share of prefixes), and every
attempt outcome feeds the balancer and the global circuit breaker.
Repeated forward failures quarantine the replica exactly the way the
supervisor quarantines a flapping engine.

RESUMABLE SESSIONS ("RPC Considered Harmful": the transport must not
re-do work the data plane preserved).  Every generation through the
router is a SESSION — a durable ``session_id`` plus the emitted-token
cursor record, the same cursor discipline as
:class:`~brpc_tpu.migrate.StandbySync`.  The session record lives in a
caller-owned :class:`SessionTable` that survives router restarts.  On
any interruption —

  * the CLIENT drops: the generation keeps decoding; tokens accumulate
    in the session record;
  * the REPLICA dies mid-decode: the router re-routes (prefix-affinity
    first, any healthy replica as fallback) and resumes the generation
    from ``prompt + emitted`` — bit-exact, because decode restarts at
    the exact (token, position) cursor — riding prefill-skip/page
    migration for the committed prefix rather than re-decoding it;
  * the ROUTER restarts: a new router adopting the same SessionTable
    marks in-flight sessions suspended and resumes them on reconnect —

the client reconnects with its ``session_id`` + cursor and receives
exactly the tokens past its cursor: replayed from the record first,
live after.  Exactly-once to any client view, by the cursor argument.

With ``replicate_sessions=True`` the router doubles as a migration
coordinator: at page boundaries it asks the serving replica to push
the session's committed full pages to its ring BUDDY (the replica a
failover would land on) over the ``_kvmig`` ``PushTo`` RPC — so a
resume after a replica kill prefix-hits pages that crossed DCN before
the crash, and ``re_decoded_tokens < total``.

THE OVERLOAD GRADIENT.  One :class:`~brpc_tpu.serving.ladder.
OverloadLadder` (the escalation/hysteresis policy extracted from the
supervisor) over cluster-wide pressures, four levels, each shedding at
the cheapest layer that still relieves the pressure:

  level 1  SHED AT ROUTER — new sessions refused with ELIMIT and a
           ``retry_after_s`` hint, before the request ever crosses DCN
           (driven by the server-level concurrency limiter and the
           session-capacity ratio);
  level 2  + BROWNOUT AT BATCHER — every replica sheds its
           deadline-less lane at admission;
  level 3  + CLAMP AT ENGINE — new generations' budgets clamped;
  level 4  + EVICT AT STORE — aggressive cache eviction each tick.

Replica supervisors keep their own local ladders; the router holds
them at a FLOOR (``EngineSupervisor.set_level_floor``) so the cluster
gradient and the local ones are one coherent ordering, and per-level
fire counters prove shed fires before brownout before clamp before
evict (and hysteresis de-escalates in reverse).

Fault sites ``router.admit`` / ``router.forward`` / ``router.resume``
thread the router into the chaos suite (scenario 14).  The ``/cluster``
console page renders the replica table, session stats, and the ladder.
"""
from __future__ import annotations

import json
import re
import threading
from brpc_tpu.butil.lockprof import InstrumentedLock
import time
import uuid
from collections import deque
from typing import Callable, Optional, Sequence

from brpc_tpu import errors, fault, rpcz
from brpc_tpu.bvar import Adder, PassiveStatus
from brpc_tpu.rpc.service import Service, method
from brpc_tpu.serving.ladder import OverloadLadder
from brpc_tpu.serving.modelplane import (DEFAULT_MODEL, CanarySplit,
                                         ModelCatalog, ModelMetrics,
                                         model_fingerprint,
                                         parse_deployments)

ROUTER_SERVICE = "Router"

# what each gradient level DOES, cheapest first — the /cluster page and
# the ordering tests key off these names
LEVEL_ACTIONS = ("shed_at_router", "brownout_at_batcher",
                 "clamp_at_engine", "evict_at_store")

# default cluster ladder: level i fires when ANY metric crosses its
# bound.  sessions_ratio = live sessions / capacity; the replica_*
# metrics are the MAX over local replica handles (same quantities the
# supervisor's in-process ladder reads).
DEFAULT_ROUTER_LADDER = (
    {"sessions_ratio": 0.80, "replica_queue_delay_us": 50_000.0,
     "replica_pool_ratio": 0.75, "replica_queue_depth": 2.0},
    {"sessions_ratio": 0.88, "replica_queue_delay_us": 100_000.0,
     "replica_pool_ratio": 0.85, "replica_queue_depth": 4.0},
    {"sessions_ratio": 0.94, "replica_queue_delay_us": 150_000.0,
     "replica_pool_ratio": 0.92, "replica_queue_depth": 6.0},
    {"sessions_ratio": 0.98, "replica_queue_delay_us": 200_000.0,
     "replica_pool_ratio": 0.96, "replica_queue_depth": 8.0},
)

# terminal codes that mean THE REPLICA broke, not the generation: the
# session survives and the driver re-routes (EOVERCROWDED means the
# ROUTER fell behind as a consumer — re-route rather than kill the
# session; tokens already recorded are never re-delivered)
FAILOVER_CODES = frozenset({errors.EFAILEDSOCKET, errors.ELOGOFF,
                            errors.EINTERNAL, errors.ERPCTIMEDOUT,
                            errors.EOVERCROWDED})


class ReplicaHandle:
    """One serving replica behind the router: its address, plus — when
    the replica lives in this process — the local components the
    cluster gradient drives directly (supervisor floor, batcher
    brownout, engine clamp, store evict).  Remote replicas are routing
    targets only; their local ladders still follow the router's shed
    because less traffic is forwarded to them."""

    def __init__(self, addr: str, *, name: Optional[str] = None,
                 supervisor=None, batcher=None, engine=None, store=None,
                 server=None, deployments=None):
        from brpc_tpu.butil.endpoint import str2endpoint
        self.addr = str(addr)
        self.endpoint = str2endpoint(self.addr)
        self.name = name or self.addr
        self.supervisor = supervisor
        self.batcher = batcher
        self.engine = engine
        self.store = store
        self.server = server
        # the replica's ReplicaDeployments (ISSUE 18), when this
        # process knows which models it serves — the router folds its
        # snapshot into the fleet catalog without an RPC
        self.deployments = deployments

    def pressures(self) -> dict:
        """This replica's local pressure triple (empty when remote)."""
        out = {}
        if self.batcher is not None:
            try:
                out["queue_delay_us"] = float(
                    self.batcher.queue_delay_rec.latency_percentile(0.99))
            except Exception:
                pass
        st = self.store
        if st is None and self.supervisor is not None:
            st = self.supervisor.store
        if st is not None:
            try:
                s = st.pagepool.stats()
                cap = s["max_blocks"] * s["pages_per_block"]
                if cap:
                    out["pool_ratio"] = s["pages_in_use"] / cap
            except Exception:
                pass
        eng = self.engine
        if eng is None and self.supervisor is not None:
            eng = self.supervisor.engine
        if eng is not None:
            try:
                out["queue_depth"] = eng.queue_depth()
            except Exception:
                pass
        return out


class Session:
    """One durable generation through the router: the prompt, the
    budget, and the emitted-token record that IS the resume cursor.
    Token delivery to the (at most one) attached client is serialized
    by ``delivery_mu`` — replay-on-attach and live appends form one
    ordered stream, so a reconnecting client can neither miss nor
    double-receive a token."""

    __slots__ = ("sid", "prompt", "budget", "emitted", "state",
                 "error_code", "replica", "resumes", "re_decoded_tokens",
                 "replicated_pages", "shipped_pages", "replicate",
                 "created_t",
                 "finished_t", "trace", "mu", "delivery_mu", "_sink",
                 "_sink_done", "attach_epoch", "wal", "_sink_from",
                 "model", "t_first_tok", "t_last_tok")

    def __init__(self, sid: str, prompt: Sequence[int], budget: int,
                 model: str = DEFAULT_MODEL):
        self.sid = sid
        self.prompt = [int(t) for t in prompt]
        self.budget = int(budget)
        # the deployment this session is bound to (ISSUE 18): routing,
        # buddy placement and WAL adoption are all constrained by it
        self.model = str(model or DEFAULT_MODEL)
        # serving-latency marks for the per-model TTFT/ITL counters
        self.t_first_tok: Optional[float] = None
        self.t_last_tok: Optional[float] = None
        self.emitted: list[int] = []     # the durable cursor record
        self.state = "running"           # running|suspended|finished|failed
        self.error_code = 0
        self.replica: Optional[str] = None
        self.resumes = 0
        self.re_decoded_tokens = 0
        self.replicated_pages = 0        # pushed to the ring buddy
        self.shipped_pages = 0           # full pages already enqueued
        # per-session replication opt-out: short interactive jobs whose
        # recompute is cheaper than shipping their pages set this False
        self.replicate = True
        self.created_t = time.monotonic()
        self.finished_t: Optional[float] = None
        self.trace = rpcz.current_trace_ctx()
        self.mu = threading.Lock()
        # ordering lock for sink delivery: acquired FIRST when both are
        # needed, never held while holding mu is required by others
        self.delivery_mu = threading.Lock()
        self._sink: Optional[Callable[[int], None]] = None
        self._sink_done: Optional[Callable] = None
        self.attach_epoch = 0
        # the table's session WAL (ISSUE 16), or None: every append /
        # terminal is logged write-ahead of client delivery
        self.wal = None
        # delivery suppressed up to this cursor: a client that attached
        # AHEAD of the record (its tokens outran a failed WAL append
        # before the crash) must not re-receive the re-decoded gap
        self._sink_from = 0

    @property
    def cursor(self) -> int:
        return len(self.emitted)

    def append(self, tok: int) -> int:
        """Record one decoded token (the write-ahead: the record is
        always a superset of any client's view) and deliver it to the
        attached client, detaching on a dead sink.  Returns the new
        cursor."""
        with self.delivery_mu:
            with self.mu:
                self.emitted.append(int(tok))
                cur = len(self.emitted)
                sink = self._sink if cur > self._sink_from else None
            if self.wal is not None:
                # WRITE-AHEAD: the durable record reaches disk before
                # the client can see the token, so a successor process
                # replaying the WAL is never behind a presented cursor.
                # append_tok never raises — a failed append parks on
                # the WAL's self-healing pending tail and this session
                # degrades to recompute-on-resume, never a lost token.
                self.wal.append_tok(self.sid, int(tok), cur)
            if sink is not None:
                try:
                    sink(int(tok))
                except Exception:
                    # the client died mid-delivery: detach, keep
                    # decoding — its reconnect replays from its cursor
                    with self.mu:
                        if self._sink is sink:
                            self._sink = None
                            self._sink_done = None
            return cur

    def attach(self, cursor: int, sink: Callable[[int], None],
               sink_done: Optional[Callable] = None) -> int:
        """Attach (or re-attach) a client at ``cursor``: replay every
        recorded token past it, then subscribe for live tokens.  A
        newer attach wins (the previous client is detached).  Returns
        the number of tokens replayed.  If the session already
        finished, the terminal is delivered after the replay.

        A cursor AHEAD of the record is legal while the session can
        still decode (ISSUE 16): it means the client saw tokens whose
        WAL append failed before a crash.  The driver re-decodes the
        gap bit-exact and delivery stays suppressed up to the cursor
        (``_sink_from``), so the client receives exactly the tokens
        past its cursor — recompute-on-resume, never a duplicate.  On
        a TERMINAL session the record can't grow, so a cursor beyond
        it is a client error as before."""
        if cursor < 0:
            raise errors.RpcError(errors.EREQUEST,
                                  f"negative cursor {cursor}")
        with self.delivery_mu:
            with self.mu:
                if cursor > len(self.emitted) and \
                        self.state in ("finished", "failed"):
                    raise errors.RpcError(
                        errors.EREQUEST,
                        f"cursor {cursor} outside the recorded stream "
                        f"({len(self.emitted)} tokens)")
                self.attach_epoch += 1
                self._sink = None        # fence the previous client
                self._sink_done = None
                self._sink_from = cursor
                backlog = self.emitted[cursor:]
                state, err_code = self.state, self.error_code
            for t in backlog:
                sink(t)
            if state in ("finished", "failed"):
                if sink_done is not None:
                    err = None if not err_code else errors.RpcError(
                        err_code, "session terminal (replayed)")
                    sink_done(err)
            else:
                with self.mu:
                    self._sink = sink
                    self._sink_done = sink_done
            return len(backlog)

    def detach(self) -> None:
        with self.delivery_mu:
            with self.mu:
                self._sink = None
                self._sink_done = None

    def finish(self, err) -> bool:
        """Deliver the terminal exactly once.  Returns False when the
        session already reached a terminal state."""
        with self.delivery_mu:
            with self.mu:
                if self.state in ("finished", "failed"):
                    return False
                self.state = "failed" if err is not None else "finished"
                self.error_code = err.code if err is not None else 0
                self.finished_t = time.monotonic()
                sink_done = self._sink_done
                self._sink = None
                self._sink_done = None
            if self.wal is not None:
                # terminal logged ahead of delivery, same discipline
                self.wal.append_fin(self.sid, self.error_code)
            if sink_done is not None:
                try:
                    sink_done(err)
                except Exception:
                    pass
            return True

    def snapshot(self) -> dict:
        with self.mu:
            return {
                "session_id": self.sid,
                "state": self.state,
                "model": self.model,
                "prompt_len": len(self.prompt),
                "budget": self.budget,
                "cursor": len(self.emitted),
                "replica": self.replica,
                "resumes": self.resumes,
                "re_decoded_tokens": self.re_decoded_tokens,
                "replicated_pages": self.replicated_pages,
                "error_code": self.error_code,
            }


class SessionTable:
    """The durable session record store — CALLER-owned, like the KV
    store is to the engine: a router restart builds a new
    :class:`ClusterRouter` over the SAME table and every in-flight
    session resumes instead of recomputing.  Finished sessions are
    kept (bounded ring) so a late reconnect can still replay its
    tail.

    With ``wal=`` a path (or a :class:`~brpc_tpu.serving.session_wal.
    SessionWAL`), every mutation is also logged write-ahead of client
    delivery, and :meth:`recover` rebuilds the table in a FRESH
    PROCESS — the durable half of the control plane (ISSUE 16)."""

    def __init__(self, *, keep_finished: int = 512, wal=None):
        self._mu = InstrumentedLock("router.sessions")
        self._sessions: dict[str, Session] = {}
        self._finished: deque = deque(maxlen=max(keep_finished, 1))
        self.keep_finished = int(keep_finished)
        self.opened_total = 0
        self.replay_stats: Optional[dict] = None
        if wal is not None and not hasattr(wal, "append_tok"):
            from brpc_tpu.serving.session_wal import SessionWAL
            wal = SessionWAL(str(wal))
        self.wal = wal
        if wal is not None:
            wal.snapshot_source = self._wal_snapshot

    @classmethod
    def recover(cls, path, *, keep_finished: int = 512,
                **wal_kwargs) -> "SessionTable":
        """Rebuild a table from a session WAL in a fresh process: every
        recovered non-terminal session comes back SUSPENDED (its driver
        died with the old router) at its recorded cursor, terminal ones
        land in the keep-ring so late reconnects still replay.  The
        replayed state is immediately compacted (adoption is the
        natural compaction point) and ``replay_stats`` records what the
        adoption replayed for the /cluster page."""
        from brpc_tpu.serving.session_wal import SessionWAL
        wal = SessionWAL(str(path), **wal_kwargs)
        t = cls(keep_finished=keep_finished, wal=wal)
        recovered, wal.recovered = wal.recovered, {}
        live = finished = 0
        for sid, rec in recovered.items():
            s = Session(sid, rec["prompt"], rec["budget"],
                        rec.get("model") or DEFAULT_MODEL)
            s.emitted = list(rec["emitted"])
            s.state = rec["state"]
            s.error_code = rec["error_code"]
            if s.state == "running":
                s.state = "suspended"
            s.wal = wal
            t._sessions[sid] = s
            if s.state in ("finished", "failed"):
                s.finished_t = time.monotonic()
                t._finished.append(s)
                finished += 1
            else:
                live += 1
        t.opened_total = len(recovered)
        t.replay_stats = dict(wal.replay)
        t.replay_stats.update({"live": live, "finished": finished})
        wal.compact()
        return t

    def _wal_snapshot(self) -> list[dict]:
        """Compaction source: the full current state of every session
        still in the table, as the dicts a ``snap`` record holds.
        Called UNDER the WAL lock (wal._mu -> table._mu -> session.mu
        is the documented order)."""
        with self._mu:
            sessions = list(self._sessions.values())
        out = []
        for s in sessions:
            with s.mu:
                out.append({"sid": s.sid, "prompt": list(s.prompt),
                            "budget": s.budget,
                            "emitted": list(s.emitted),
                            "state": s.state,
                            "error_code": s.error_code,
                            "model": s.model})
        return out

    def new_session(self, prompt: Sequence[int], budget: int,
                    model: str = DEFAULT_MODEL) -> Session:
        sid = uuid.uuid4().hex[:16]
        s = Session(sid, prompt, budget, model)
        s.wal = self.wal
        with self._mu:
            self._sessions[sid] = s
            self.opened_total += 1
        if self.wal is not None:
            # logged after the insert but before any token can flow
            # (the driver starts only after open_session returns)
            self.wal.append_open(sid, s.prompt, s.budget, model=s.model)
        return s

    def get(self, sid: str) -> Optional[Session]:
        with self._mu:
            return self._sessions.get(sid)

    def note_finished(self, s: Session) -> None:
        """Move a finished session into the bounded keep-ring (evicting
        the oldest finished record past capacity)."""
        with self._mu:
            if s.sid not in self._sessions:
                return
            if len(self._finished) == self._finished.maxlen:
                old = self._finished[0]
                self._sessions.pop(old.sid, None)
            self._finished.append(s)

    def suspend_running(self) -> int:
        """Mark every running session suspended (router shutdown /
        crash adoption): a later attach restarts its driver."""
        n = 0
        with self._mu:
            sessions = list(self._sessions.values())
        for s in sessions:
            with s.mu:
                if s.state == "running":
                    s.state = "suspended"
                    n += 1
        return n

    def counts(self) -> dict:
        with self._mu:
            sessions = list(self._sessions.values())
        out = {"running": 0, "suspended": 0, "finished": 0, "failed": 0}
        for s in sessions:
            out[s.state] = out.get(s.state, 0) + 1
        out["total"] = len(sessions)
        out["opened_total"] = self.opened_total
        return out

    def live_count(self) -> int:
        with self._mu:
            sessions = list(self._sessions.values())
        return sum(1 for s in sessions
                   if s.state in ("running", "suspended"))

    def counts_by_model(self) -> dict:
        """Per-deployment session-state counts (the /cluster catalog
        panel's per-model column, ISSUE 18)."""
        with self._mu:
            sessions = list(self._sessions.values())
        out: dict[str, dict] = {}
        for s in sessions:
            row = out.setdefault(s.model, {"running": 0, "suspended": 0,
                                           "finished": 0, "failed": 0})
            row[s.state] = row.get(s.state, 0) + 1
        return out

    def snapshot(self, limit: int = 50) -> list[dict]:
        with self._mu:
            sessions = list(self._sessions.values())
        sessions.sort(key=lambda s: s.created_t)
        return [s.snapshot() for s in sessions[-limit:]]

    def close(self) -> None:
        """Close the table's WAL (if any).  The table itself needs no
        teardown — it is plain caller-owned state."""
        if self.wal is not None:
            self.wal.close()


class _ForwardCollector:
    """Stream handler for ONE forward attempt: tokens go straight into
    the session record (which fans them to the attached client), the
    terminal latches here for the driver to classify."""

    def __init__(self, router: "ClusterRouter", session: Session):
        self.router = router
        self.session = session
        self.error: Optional[int] = None
        self.prefix_hit = 0
        self.done = threading.Event()
        self._terminal_seen = False

    def on_received_messages(self, stream, messages):
        for m in messages:
            try:
                d = json.loads(m)
            except ValueError:
                continue
            if "token" in d:
                cur = self.session.append(int(d["token"]))
                self.router._on_session_progress(self.session, cur)
            if d.get("done"):
                self._terminal_seen = True
                if d.get("error"):
                    self.error = int(d["error"])
                self.done.set()

    def on_closed(self, stream):
        if not self._terminal_seen and self.error is None:
            # the stream died under the generation (replica kill,
            # socket loss): a truncated stream is a FAILOVER, never a
            # completed generation (an error already latched — e.g.
            # the driver's progress deadline — is kept)
            self.error = errors.EFAILEDSOCKET
        self.done.set()

    def on_idle_timeout(self, stream):
        pass


class ClusterRouter:
    """The routing service in front of N serving replicas (see module
    docstring).  ``replicas`` is a sequence of addresses or
    :class:`ReplicaHandle`\\ s; pass ``sessions=`` an existing
    :class:`SessionTable` to adopt a previous router's sessions."""

    def __init__(self, replicas: Sequence, *,
                 sessions: Optional[SessionTable] = None,
                 wal=None,
                 limiter=None,
                 max_sessions: int = 256,
                 ladder: Sequence[dict] = DEFAULT_ROUTER_LADDER,
                 hysteresis_ticks: int = 3,
                 check_interval_s: float = 0.05,
                 auto_tick: bool = True,
                 replicate_sessions: bool = False,
                 replication_factor: int = 2,
                 page_tokens: int = 16,
                 chunk_tokens: int = 16,
                 clamp_new_tokens: int = 32,
                 ladder_evict_pages: Optional[int] = None,
                 quarantine_after: int = 3,
                 failure_window_s: float = 60.0,
                 name: str = "router",
                 timeout_ms: int = 10_000,
                 control_timeout_ms: int = 2_000,
                 epoch: Optional[int] = None,
                 progress_timeout_s: float = 30.0,
                 default_model: str = DEFAULT_MODEL,
                 telemetry_collect: bool = True,
                 telemetry_pull_interval_s: float = 0.25):
        from brpc_tpu.policy.load_balancer import PrefixAffinityLB
        from brpc_tpu.rpc.channel import Channel
        from brpc_tpu.rpc.combo_channels import SelectiveChannel

        self.name = name
        self.timeout_ms = int(timeout_ms)
        self.control_timeout_ms = int(control_timeout_ms)
        self.progress_timeout_s = float(progress_timeout_s)
        self.chunk_tokens = int(chunk_tokens)
        self.page_tokens = int(page_tokens)
        self.max_sessions = int(max_sessions)
        self.clamp_new_tokens = int(clamp_new_tokens)
        self.ladder_evict_pages = ladder_evict_pages
        self.quarantine_after = int(quarantine_after)
        self.failure_window_s = float(failure_window_s)
        self.replicate_sessions = bool(replicate_sessions)
        # N-way placement (ISSUE 16): total copies of a prefix on the
        # affinity ring — the owner plus replication_factor-1 buddies
        self.replication_factor = max(1, int(replication_factor))
        self.check_interval_s = float(check_interval_s)
        self.default_model = str(default_model or DEFAULT_MODEL)

        self.replicas: list[ReplicaHandle] = [
            r if isinstance(r, ReplicaHandle) else ReplicaHandle(r)
            for r in replicas]
        if not self.replicas:
            raise ValueError("a router needs at least one replica")
        self._lb = PrefixAffinityLB()
        self._sel = SelectiveChannel(max_retry=len(self.replicas),
                                     lb=self._lb)
        self._by_ep: dict = {}
        self._chan_by_ep: dict = {}
        self._ep_by_name: dict = {}      # str(endpoint) / addr -> endpoint
        for h in self.replicas:
            ch = Channel(h.addr, timeout_ms=self.timeout_ms)
            self._sel.add_channel(ch, endpoint=h.endpoint)
            self._by_ep[h.endpoint] = h
            self._chan_by_ep[h.endpoint] = ch
            self._ep_by_name[str(h.endpoint)] = h.endpoint
            self._ep_by_name[h.addr] = h.endpoint

        if sessions is not None:
            self.sessions = sessions
        else:
            self.sessions = SessionTable(wal=wal)
        # adopting a table from a dead router: its running sessions have
        # no driver anymore — suspend them so attach restarts the drive
        self.sessions.suspend_running()

        # membership epoch (ISSUE 16): every floor push carries it and
        # replicas fence pushes from superseded routers.  A router over
        # a WAL bumps the PERSISTED epoch so a successor process always
        # strictly supersedes the router whose log it adopted.
        if epoch is not None:
            self.epoch = int(epoch)
        elif self.sessions.wal is not None:
            self.epoch = self.sessions.wal.bump_epoch()
        else:
            self.epoch = 1

        if limiter is not None:
            from brpc_tpu.policy.concurrency_limiter import create_limiter
            limiter = create_limiter(limiter)
        self.limiter = limiter

        self._ladder = OverloadLadder(ladder,
                                      hysteresis_ticks=hysteresis_ticks)
        self._applied_level = 0
        self._mu = InstrumentedLock("router.state")
        self._failures: dict = {}        # endpoint -> [monotonic times]
        self._drivers: dict[str, threading.Thread] = {}

        # wire-level overload (ISSUE 16): per-remote-replica floor-push
        # state (epoch/level acked, push/ack times, last error) and the
        # freshest pressure report each SetFloor reply carried back
        self._ctrl_chan_by_ep: dict = {}
        self._remote_floor: dict = {}    # endpoint -> state dict
        self.floor_pushes = 0
        self.floor_push_drops = 0
        self.floor_push_refused = 0

        # ownership directory (ISSUE 16): prefix fingerprint -> where
        # its pages actually are (owner + buddies that acked a push) —
        # forwarded as the prefix_holders hint so a cache-miss replica
        # can PULL the prefix instead of recomputing.  Keys are MODEL
        # fingerprints (ISSUE 18), so two models sharing a prompt can
        # never read each other's placement.
        from collections import OrderedDict
        self._placement_dir: "OrderedDict[int, dict]" = OrderedDict()
        self._placement_cap = 256

        # the multi-model plane (ISSUE 18): fleet catalog (who serves
        # what, in which lifecycle state), the canary version splitter,
        # and the per-(model,version) serving counters
        self.catalog = ModelCatalog()
        self.canary = CanarySplit()
        self.model_metrics = ModelMetrics()
        for h in self.replicas:
            if getattr(h, "deployments", None) is not None:
                self.catalog.note(h.addr, h.deployments.snapshot())

        # fleet telemetry plane (ISSUE 20): the router-LOCAL half
        # (scoreboard sampling + SLO evaluation) runs every tick, but
        # the per-endpoint _telemetry pulls ride their own slower
        # cadence — a pull ships a full bvar snapshot both sides must
        # JSON-encode/decode under the GIL, and at the 20 Hz overload
        # tick that tax alone breaks the <2% overhead gate while SLO
        # windows are seconds-scale and gain nothing from it
        from brpc_tpu.serving.telemetry import FleetCollector
        self.telemetry_collect = bool(telemetry_collect)
        self.telemetry_pull_interval_s = float(telemetry_pull_interval_s)
        self._last_pull_t = 0.0
        self.collector = FleetCollector(
            name, control_timeout_ms=self.control_timeout_ms)
        self.slo = None
        self._floor_sources: list = []

        safe = re.sub(r"\W", "_", name)
        from brpc_tpu.bvar.variable import exposed_variables
        pre = set(exposed_variables(f"router_{safe}*"))
        self.shed_total = Adder(f"router_{safe}_shed")
        self.forwards = Adder(f"router_{safe}_forwards")
        self.resumes_total = Adder(f"router_{safe}_resumes")
        self.replays_total = Adder(f"router_{safe}_replayed_tokens")
        self.reconnects = Adder(f"router_{safe}_reconnects")
        # mis-routes the model constraint caught (a pick landing on a
        # replica that does not serve the session's model — stale
        # catalog or injected router.model_route): MUST stay 0 in any
        # healthy run (rpc_press --models asserts it)
        self.wrong_model_routes = Adder(f"router_{safe}_wrong_model_routes")
        # per-level gradient action counters — the ordering proof
        self.gradient_fired = {
            a: Adder(f"router_{safe}_{a}") for a in LEVEL_ACTIONS}
        PassiveStatus(lambda: self._ladder.level).expose(
            f"router_{safe}_level")
        self._bvar_names = [n for n in exposed_variables(f"router_{safe}*")
                            if n not in pre]

        # buddy replication worker (resume-over-migration): PushTo jobs
        # coalesce per session, never ride the token path
        self._ship_cv = threading.Condition(
            InstrumentedLock("router.ship"))
        self._ship_q: deque = deque()
        self._ship_pending: set[str] = set()

        self._running = True
        self._threads: list[threading.Thread] = []
        if self.replicate_sessions:
            t = threading.Thread(target=self._ship_loop, daemon=True,
                                 name=f"router-ship-{safe}")
            t.start()
            self._threads.append(t)
        if auto_tick:
            t = threading.Thread(target=self._tick_loop, daemon=True,
                                 name=f"router-ladder-{safe}")
            t.start()
            self._threads.append(t)

        from brpc_tpu import serving as _serving
        _serving._register_router(self)

    # ---- admission (gradient level 1 lives here) ----

    def retry_after_s(self) -> float:
        """The Retry-After hint attached to a router shed: one full
        de-escalation window — earlier retries would land inside the
        same overload plateau and be shed again."""
        return round(max(0.25, self._ladder.hysteresis_ticks *
                         self.check_interval_s), 3)

    def resolve_model(self, model: Optional[str] = None) -> str:
        """Resolve a request's ``model`` field to one deployment key
        (ISSUE 18): absent -> the sole deployment (or the default
        model), a bare ``model_id`` with several versions -> the canary
        split over the published version weights.  Unknown models raise
        EREQUEST — the misroute never leaves the front door."""
        cat = self.catalog
        if not model:
            if cat.empty():
                return self.default_model
            sole = cat.sole_key()
            if sole is not None:
                return sole
            model = self.default_model
        model = str(model)
        if cat.empty():
            # no catalog published: the pre-plane single-model fleet —
            # only the default model exists
            if model == self.default_model:
                return model
            raise errors.RpcError(
                errors.EREQUEST,
                f"unknown model {model!r}: this router serves only "
                f"{self.default_model!r}")
        keys = cat.resolve(model)
        if not keys:
            raise errors.RpcError(
                errors.EREQUEST,
                f"unknown model {model!r}; deployed: {cat.keys()}")
        if len(keys) == 1:
            return keys[0]
        weights = {k: w for k, w in cat.version_weights(model).items()
                   if k in keys}
        if not weights:
            weights = {k: 1 for k in keys}
        return self.canary.pick(model, weights)

    def open_session(self, prompt: Sequence[int],
                     max_new_tokens: int,
                     model: Optional[str] = None) -> Session:
        """Admit one generation: shed-at-router (ELIMIT with a
        ``retry_after_s`` hint in the error text) before anything
        crosses DCN, else create the durable session and start its
        driver."""
        if fault.ENABLED and fault.hit("router.admit",
                                       name=self.name) is not None:
            raise errors.RpcError(errors.EINTERNAL,
                                  "injected router admit failure")
        model = self.resolve_model(model)
        live = self.sessions.live_count()
        shed_text = None
        if not self._running:
            raise errors.RpcError(errors.ELOGOFF, "router closed")
        if self._ladder.level >= 1:
            shed_text = (f"overload gradient level {self._ladder.level}: "
                         f"shed at router")
        elif self.limiter is not None and \
                not self.limiter.on_requested(live + 1):
            shed_text = "router concurrency limiter rejected the session"
        elif live + 1 > self.max_sessions:
            shed_text = (f"session capacity {self.max_sessions} reached")
        if shed_text is not None:
            self.shed_total.add(1)
            self.gradient_fired["shed_at_router"].add(1)
            self.model_metrics.note_shed(model)
            raise errors.RpcError(
                errors.ELIMIT,
                f"{shed_text}; retry_after_s={self.retry_after_s()}")
        s = self.sessions.new_session(prompt, max_new_tokens,
                                      model=model)
        self.model_metrics.note_open(model)
        self._start_driver(s)
        return s

    def attach(self, sid: str, cursor: int,
               sink: Callable[[int], None],
               sink_done: Optional[Callable] = None) -> dict:
        """Client (re)connect: replay the recorded tokens past
        ``cursor``, subscribe for live ones, and — when the session was
        suspended (router restart / dead driver) — restart the drive.
        Returns ``{"replayed": n, "cursor": new_cursor}``."""
        if not self._running:
            # a closed router can no longer drive a suspended session:
            # tell the client now (reconnect to the successor) instead
            # of replaying a backlog that never reaches a terminal
            raise errors.RpcError(errors.ELOGOFF, "router closed")
        if fault.ENABLED and fault.hit("router.resume", sid=sid) is not None:
            raise errors.RpcError(errors.EINTERNAL,
                                  "injected router resume failure")
        s = self.sessions.get(sid)
        if s is None:
            raise errors.RpcError(errors.EREQUEST,
                                  f"unknown session {sid!r}")
        replayed = s.attach(cursor, sink, sink_done)
        if replayed:
            self.replays_total.add(replayed)
        self.reconnects.add(1)
        restart = False
        with s.mu:
            if s.state == "suspended":
                s.state = "running"
                restart = True
        if restart:
            self._start_driver(s)
        return {"replayed": replayed, "cursor": cursor + replayed}

    # ---- the session driver (forward + failover) ----

    def _start_driver(self, s: Session) -> None:
        t = threading.Thread(target=self._drive, args=(s,), daemon=True,
                             name=f"router-session-{s.sid[:8]}")
        with self._mu:
            self._drivers[s.sid] = t
        t.start()

    def _fp_for(self, model: str, prompt: Sequence[int]) -> int:
        """The session's ring key: the ``(model, prefix)`` fingerprint,
        with the router's default model mapping to the plain prefix
        fingerprint (single-model placement identical to pre-plane)."""
        m = None if model == self.default_model else model
        return model_fingerprint(m, prompt, self.chunk_tokens)

    def _allowed_eps(self, model: str) -> Optional[set]:
        """Endpoints serving ``model`` for NEW placements (warm or
        loading; draining replicas only finish what they hold), or
        ``None`` when no catalog is published — the unconstrained
        pre-plane fleet."""
        cat = self.catalog
        if cat.empty():
            return None
        eps = set()
        for addr in cat.replicas_for(model, for_new=True):
            ep = self._ep_by_name.get(addr)
            if ep is not None:
                eps.add(ep)
        return eps

    def _drive(self, s: Session) -> None:
        from brpc_tpu.rpc.controller import Controller
        from brpc_tpu.rpc.stream import stream_create
        fp = self._fp_for(s.model, s.prompt)
        excluded: set = set()
        attempts = 0
        max_attempts = 3 * len(self.replicas) + 3
        # a session with recorded tokens at drive entry is a RESUME
        # (router restart / WAL adoption): its first forward re-sends
        # prompt+emitted and must account re-decoded tokens like any
        # mid-drive failover would
        first_attempt = s.cursor == 0
        try:
            while self._running:
                with s.mu:
                    if s.state != "running":
                        return
                    remaining = s.budget - len(s.emitted)
                    resume_prompt = s.prompt + s.emitted
                if remaining <= 0:
                    self._finish_session(s, None)
                    return
                attempts += 1
                if attempts > max_attempts:
                    self._finish_session(s, errors.RpcError(
                        errors.EINTERNAL,
                        f"router gave up after {attempts - 1} forward "
                        f"attempts"))
                    return
                if attempts > 1:
                    # bounded backoff between attempts: a refusal storm
                    # must not burn the whole attempt budget before
                    # health marking / breaker recovery can land
                    time.sleep(min(0.25, 0.01 * (attempts - 1)))
                # the model constraint (ISSUE 18): the pick may only
                # land on a replica the catalog says serves s.model —
                # re-read each attempt, a deploy/drain can land mid-
                # session and the failover must honor the new state
                allowed = self._allowed_eps(s.model)
                if allowed is not None and not allowed:
                    self._finish_session(s, errors.RpcError(
                        errors.ENODATA,
                        f"no replica serves model {s.model!r}"))
                    return
                constraint = (set(self._by_ep) - allowed
                              if allowed is not None else set())
                picked = self._sel.pick(exclude=excluded | constraint,
                                        request_code=fp)
                if picked is not None and allowed is not None \
                        and picked[2] not in allowed:
                    # the ring's last-resort fallback handed back an
                    # excluded endpoint: treat as unroutable this round
                    picked = None
                if picked is None and excluded:
                    # everything healthy was tried this round: start a
                    # fresh round (a probe may have revived someone)
                    excluded = set()
                    picked = self._sel.pick(exclude=constraint,
                                            request_code=fp)
                    if picked is not None and allowed is not None \
                            and picked[2] not in allowed:
                        picked = None
                if picked is None:
                    self._finish_session(s, errors.RpcError(
                        errors.ENODATA,
                        f"no routable replica serves model {s.model!r}"
                        if allowed is not None
                        else "no routable replica"))
                    return
                _i, chan, ep = picked
                if fault.ENABLED and fault.hit(
                        "router.model_route", model=s.model,
                        replica=str(ep)) is not None:
                    # injected catalog staleness: the pick is treated
                    # as a mis-route — counted (the invariant press
                    # asserts on) and re-routed, never forwarded
                    self.wrong_model_routes.add(1)
                    excluded.add(ep)
                    first_attempt = False
                    continue
                if allowed is not None and ep not in allowed:
                    # defense in depth: a stale catalog let a non-
                    # serving replica through — count and re-route
                    self.wrong_model_routes.add(1)
                    excluded.add(ep)
                    first_attempt = False
                    continue
                if not first_attempt:
                    with s.mu:
                        s.resumes += 1
                    self.resumes_total.add(1)
                if fault.ENABLED and fault.hit(
                        "router.forward", replica=str(ep)) is not None:
                    self._note_replica_failure(ep, errors.EINTERNAL)
                    excluded.add(ep)
                    first_attempt = False
                    continue
                col = _ForwardCollector(self, s)
                cntl = Controller(timeout_ms=self.timeout_ms)
                stream = stream_create(cntl, col)
                t0 = time.monotonic()
                fwd = {"prompt": resume_prompt,
                       "max_new_tokens": remaining}
                if not self.catalog.empty():
                    # name the deployment so a multi-model replica
                    # resolves the right engine (a single-model fleet
                    # keeps the pre-plane wire shape)
                    fwd["model"] = s.model
                holders = self._holders_for(fp, exclude_addr=str(ep))
                if holders:
                    # pull-based prefix fetch (ISSUE 16): tell the
                    # target where this prefix's pages already are so a
                    # cache miss warms itself from an owner over the
                    # migrator instead of re-prefilling
                    fwd["prefix_holders"] = holders
                try:
                    resp = chan.call_sync(
                        "Serving", "Generate", fwd,
                        serializer="json", cntl=cntl)
                except errors.RpcError as e:
                    # the forward RPC itself failed (replica server
                    # gone): channel layer already fed the breaker.
                    # The never-bound stream must close here or it
                    # leaks in the StreamRegistry forever (no socket
                    # failure can ever reap it)
                    try:
                        stream.close()
                    except Exception:
                        pass
                    self._sel.feedback(ep, e.code, breaker=False)
                    self._note_replica_failure(ep, e.code)
                    excluded.add(ep)
                    first_attempt = False
                    continue
                self.forwards.add(1)
                buddy_addr = self._by_ep.get(ep)
                self._note_placement(fp, owner=(
                    buddy_addr.addr if buddy_addr is not None
                    else str(ep)))
                hit = int((resp or {}).get("prefix_hit", 0))
                with s.mu:
                    s.replica = str(ep)
                    if not first_attempt:
                        # what this failover actually re-decodes: the
                        # resume prompt minus what the new replica's
                        # cache already held (committed prefix ridden
                        # via prefill-skip / page migration)
                        s.re_decoded_tokens += max(
                            0, len(resume_prompt) - hit)
                # wait out the attempt; wake periodically so a closing
                # router suspends instead of blocking forever, and
                # watch a PROGRESS deadline — a replica that accepted
                # the forward but neither emits nor closes (server
                # alive, engine wedged) must read as a failover, not
                # hang the session until router close
                last_cursor = s.cursor
                last_progress = time.monotonic()
                while self._running:
                    if col.done.wait(0.1):
                        break
                    cur = s.cursor
                    if cur != last_cursor:
                        last_cursor = cur
                        last_progress = time.monotonic()
                    elif (time.monotonic() - last_progress
                          > self.progress_timeout_s):
                        col.error = errors.ERPCTIMEDOUT
                        try:
                            stream.close()
                        except Exception:
                            pass
                        break
                    with s.mu:
                        if s.state != "running":
                            break
                with s.mu:
                    still_running = s.state == "running"
                if not self._running or not still_running:
                    try:
                        stream.close()
                    except Exception:
                        pass
                    return
                latency_us = int((time.monotonic() - t0) * 1e6)
                if col.error is None:
                    self._sel.feedback(ep, 0, latency_us, breaker=False)
                    self._finish_session(s, None)
                    return
                if col.error in FAILOVER_CODES:
                    # replica failure mid-stream: quarantine evidence,
                    # re-route, resume after the recorded cursor
                    self._sel.feedback(ep, col.error, latency_us,
                                       breaker=True)
                    self._note_replica_failure(ep, col.error)
                    excluded = {ep}
                    first_attempt = False
                    continue
                # the generation's own terminal error: definite
                self._finish_session(s, errors.RpcError(
                    col.error, "replica terminal error"))
                return
            # router closing: suspend (a successor adopts the table)
            with s.mu:
                if s.state == "running":
                    s.state = "suspended"
        finally:
            with self._mu:
                self._drivers.pop(s.sid, None)

    def cancel_session(self, s: Session, err=None) -> None:
        """Abort a session no client can ever reach (e.g. its Generate
        attach failed after admission): deliver the terminal, release
        the limiter slot, and let the driver notice the state flip and
        stop forwarding — without this, the orphan decodes its whole
        budget for nobody while counting against ``max_sessions``."""
        if err is None:
            err = errors.RpcError(errors.ELOGOFF, "session cancelled")
        self._finish_session(s, err)

    def _finish_session(self, s: Session, err) -> None:
        if s.finish(err):
            code = err.code if err is not None else 0
            if self.limiter is not None:
                dur_us = int((time.monotonic() - s.created_t) * 1e6)
                self.limiter.on_responded(code, dur_us)
            self.sessions.note_finished(s)

    def _note_replica_failure(self, ep, code: int) -> None:
        """Forward-failure evidence: feeds the breaker's isolation
        counter and — past ``quarantine_after`` failures inside the
        window — marks the endpoint broken, exactly the supervisor's
        flapping-replica discipline.  The prefix-affinity ring then
        walks past it, remapping only ITS share of prefixes."""
        now = time.monotonic()
        with self._mu:
            times = self._failures.setdefault(ep, [])
            times.append(now)
            times[:] = [t for t in times
                        if t > now - self.failure_window_s]
            n = len(times)
        try:
            from brpc_tpu.policy.circuit_breaker import global_breaker
            breaker = global_breaker()
            breaker.on_socket_failed(ep)
            if n >= self.quarantine_after:
                breaker.mark_as_broken(ep)
        except Exception:
            import logging
            logging.getLogger(__name__).exception(
                "router replica-failure report failed")

    # ---- buddy replication (resume-over-migration) ----

    def _on_session_progress(self, s: Session, cursor: int) -> None:
        # per-(model,version) latency counters (ISSUE 18): one writer
        # per session (the collector thread), so the marks need no lock
        now = time.monotonic()
        if s.t_first_tok is None:
            s.t_first_tok = now
            self.model_metrics.note_ttft(s.model, now - s.created_t)
        elif s.t_last_tok is not None:
            self.model_metrics.note_itl(s.model, now - s.t_last_tok)
        s.t_last_tok = now
        if not self.replicate_sessions or not s.replicate:
            return
        with s.mu:
            full = (len(s.prompt) + cursor) // self.page_tokens
            ship = full > s.shipped_pages
            if ship:
                s.shipped_pages = full
        if ship:
            with self._ship_cv:
                if s.sid not in self._ship_pending:
                    self._ship_pending.add(s.sid)
                    self._ship_q.append(s.sid)
                    self._ship_cv.notify()

    def _ship_loop(self) -> None:
        from brpc_tpu.butil import stagetag
        while True:
            with self._ship_cv:
                while self._running and not self._ship_q:
                    self._ship_cv.wait(0.25)
                if not self._running:
                    return
                sid = self._ship_q.popleft()
                self._ship_pending.discard(sid)
            with stagetag.stage("migrate"):
                try:
                    self._ship_one(sid)
                except Exception:
                    import logging
                    logging.getLogger(__name__).info(
                        "session buddy replication failed", exc_info=True)

    def _ship_one(self, sid: str) -> None:
        """Ask the session's serving replica to push its committed
        full pages to its ring BUDDIES — the ``replication_factor - 1``
        ring successors a failover of this prefix would land on — over
        the ``_kvmig`` PushTo RPC, and record the resulting N-way
        placement in the ownership directory.  A failing push degrades
        the future resume to recompute; it never touches the token
        path."""
        s = self.sessions.get(sid)
        if s is None:
            return
        with s.mu:
            if s.state != "running" or s.replica is None:
                return
            toks = s.prompt + s.emitted
            cur_addr = s.replica
        cur_ep = self._ep_by_name.get(cur_addr)
        fp = self._fp_for(s.model, s.prompt)
        # buddy placement constrained to SAME-MODEL holders (ISSUE 18):
        # a failover can only land on a replica serving s.model, so
        # only those are worth warming
        ex = {cur_ep} if cur_ep is not None else set()
        allowed = self._allowed_eps(s.model)
        if allowed is not None:
            ex |= set(self._by_ep) - allowed
        buddies = self._lb.placement(
            fp, self.replication_factor, exclude=ex or None)
        if allowed is not None:
            buddies = [b for b in buddies if b in allowed]
        buddies = [b for b in buddies if str(b) != cur_addr]
        buddies = buddies[:max(0, self.replication_factor - 1)]
        if not buddies:
            return
        picked = self._chan_by_ep.get(cur_ep)
        if picked is None:
            return
        full = len(toks) // self.page_tokens * self.page_tokens
        if not full:
            return
        best = 0
        acked: list[str] = []
        push = {"tokens": toks[:full], "dest": None}
        if not self.catalog.empty():
            # same-model fetch constraint (ISSUE 18): a model-tagged
            # _kvmig endpoint refuses pushes for another model, so a
            # stale placement can never splice B-pages into an A-store
            push["model"] = s.model
        for buddy in buddies:
            buddy_h = self._by_ep.get(buddy)
            dest = buddy_h.addr if buddy_h is not None else str(buddy)
            push["dest"] = dest
            try:
                out = picked.call_sync(
                    "_kvmig", "PushTo", dict(push),
                    serializer="json", response_serializer="json")
            except errors.RpcError:
                # this buddy degrades to recompute; the others still
                # get their copy
                continue
            pages = int((out or {}).get("migrated_pages", 0))
            if pages:
                best = max(best, pages)
                acked.append(dest)
        self._note_placement(fp, owner=cur_addr, buddies=acked)
        if best:
            with s.mu:
                s.replicated_pages = max(s.replicated_pages, best)

    # ---- the ownership directory (N-way placement, ISSUE 16) ----

    def _note_placement(self, fp: int, *, owner: Optional[str] = None,
                        buddies: Optional[Sequence[str]] = None) -> None:
        with self._mu:
            rec = self._placement_dir.get(fp)
            if rec is None:
                rec = {"owner": None, "buddies": []}
                self._placement_dir[fp] = rec
                while len(self._placement_dir) > self._placement_cap:
                    self._placement_dir.popitem(last=False)
            else:
                self._placement_dir.move_to_end(fp)
            if owner is not None:
                rec["owner"] = str(owner)
            for b in buddies or ():
                if b not in rec["buddies"]:
                    rec["buddies"].append(str(b))

    def _holders_for(self, fp: int,
                     exclude_addr: Optional[str] = None) -> list[str]:
        """Everywhere this prefix's pages are known to be (owner first,
        then acked buddies), minus the forward target itself."""
        with self._mu:
            rec = self._placement_dir.get(fp)
            if rec is None:
                return []
            out = []
            if rec["owner"]:
                out.append(rec["owner"])
            out.extend(b for b in rec["buddies"] if b not in out)
        ex = str(exclude_addr) if exclude_addr is not None else None
        ex_ep = self._ep_by_name.get(ex) if ex is not None else None
        drop = {ex} if ex else set()
        if ex_ep is not None:
            drop.add(str(ex_ep))
            h = self._by_ep.get(ex_ep)
            if h is not None:
                drop.add(h.addr)
        return [a for a in out if a not in drop]

    def placements(self, limit: int = 32) -> list[dict]:
        """The N-way buddy placement table for the /cluster page."""
        with self._mu:
            items = list(self._placement_dir.items())[-limit:]
        return [{"fingerprint": f"{fp:016x}", "owner": rec["owner"],
                 "buddies": list(rec["buddies"])}
                for fp, rec in items]

    # ---- the cluster overload gradient ----

    def _tick_loop(self) -> None:
        while self._running:
            time.sleep(self.check_interval_s)
            if not self._running:
                return
            try:
                self._tick()
            except Exception:
                import logging
                logging.getLogger(__name__).exception(
                    "router ladder tick failed")

    def _pressures(self) -> dict:
        cap = self.max_sessions
        if self.limiter is not None:
            lim = self.limiter.max_concurrency()
            if lim > 0:
                cap = min(cap, lim)
        out = {"sessions_ratio": self.sessions.live_count() / max(1, cap)}
        qd = pool = depth = 0.0
        for h in self.replicas:
            p = h.pressures()
            if not p and self._is_remote(h):
                # remote replica: read the pressure report its last
                # SetFloor ack carried back (wire-level overload) —
                # a remote-only fleet feeds the gradient too
                st = self._remote_floor.get(h.endpoint)
                if st is not None:
                    p = st.get("pressures") or {}
            qd = max(qd, p.get("queue_delay_us", 0.0))
            pool = max(pool, p.get("pool_ratio", 0.0))
            depth = max(depth, p.get("queue_depth", 0.0))
        out["replica_queue_delay_us"] = qd
        out["replica_pool_ratio"] = pool
        out["replica_queue_depth"] = depth
        return out

    def _tick(self) -> int:
        # advisory floor sources (ISSUE 20): the ladder's floor is the
        # max over registered sources — a held MINIMUM level, never an
        # escalation; the pressure gradient stays in charge above it
        floor = 0
        for fn in self._floor_sources:
            try:
                floor = max(floor, int(fn()))
            except Exception:
                pass
        self._ladder.floor = min(floor, self._ladder.num_levels)
        lvl = self._ladder.update(self._pressures())
        self._apply_level(lvl)
        self._push_floor(lvl)
        # refresh the catalog from in-process replicas (remote ones
        # publish via their SetFloor ack in _push_floor)
        for h in self.replicas:
            if getattr(h, "deployments", None) is not None:
                self.catalog.note(h.addr, h.deployments.snapshot())
        if self.telemetry_collect:
            self._collect_telemetry()
        return lvl

    def _collect_telemetry(self) -> None:
        """One fleet-telemetry pass (ISSUE 20): sample the router-local
        per-(model, version) scoreboard into the fleet series, pull each
        endpoint's ``_telemetry`` increment over the control channel the
        SetFloor push already holds open (at most once per
        ``telemetry_pull_interval_s``), then run the SLO engine over
        the refreshed series."""
        from brpc_tpu.policy.health_check import is_broken
        c = self.collector
        c.sample_models(self.model_metrics)
        now = time.monotonic()
        if now - self._last_pull_t >= self.telemetry_pull_interval_s:
            self._last_pull_t = now
            for h in self.replicas:
                if is_broken(h.endpoint):
                    # a quarantined replica is TOMBSTONED, never
                    # pulled: pulling a dead endpoint would stall the
                    # tick thread for the control timeout every pass,
                    # and the series must show the gap, not silently
                    # average over it
                    c.note_dead(h.addr)
                    continue
                c.pull(h.addr, self._ctrl_channel(h))
        if self.slo is not None:
            try:
                self.slo.tick(c, self)
            except Exception:
                import logging
                logging.getLogger(__name__).exception("slo tick failed")

    def add_floor_source(self, fn) -> None:
        """Register an advisory floor source: a zero-arg callable whose
        value (clamped to the ladder height) joins the per-tick max
        holding the ladder's floor."""
        self._floor_sources.append(fn)

    def attach_slo(self, engine) -> None:
        """Attach an SLO burn-rate engine (``serving/slo.py``): ticked
        after every collection pass, its promote/rollback decisions ride
        this router's epoch-fenced deploy pushes and its ``floor()``
        becomes an advisory floor source."""
        self.slo = engine
        self.add_floor_source(engine.floor)

    def trace_fanout(self, trace_id: int) -> list:
        """Every collected span of one trace across the FLEET (ISSUE
        20): local + fleet-store spans plus live ``_telemetry`` Trace
        queries to each replica and to every peer address the merged
        client spans name — the hop that reaches a PS shard this router
        never talks to directly."""
        return self.collector.fan_out_trace(
            int(trace_id), addrs=[h.addr for h in self.replicas])

    def fleet_snapshot(self, points: int = 32) -> dict:
        """The /fleet console page's data for this router: collector
        state + tombstones, the windowed series rings, the per-model
        scoreboard, canary ramp state and the SLO decision trail."""
        return {
            "collector": self.collector.stats(),
            "series": self.collector.series_snapshot(points),
            "models": self.model_metrics.snapshot(),
            "canary": self.canary.snapshot(),
            "catalog": self.catalog.snapshot(),
            "slo": self.slo.snapshot() if self.slo is not None else None,
            "ladder": self._ladder.stats(),
        }

    def _apply_level(self, lvl: int) -> None:
        from brpc_tpu.serving.ladder import apply_level_to_components
        prev = self._applied_level
        if lvl > prev:
            # count each action the FIRST time the ramp reaches it —
            # the gradient-ordering proof (shed counted at the actual
            # refusals in open_session; the flag here marks the level
            # transition itself for levels without a local component)
            for step in range(prev + 1, lvl + 1):
                if 2 <= step <= len(LEVEL_ACTIONS):
                    self.gradient_fired[LEVEL_ACTIONS[step - 1]].add(1)
        self._applied_level = lvl
        for h in self.replicas:
            apply_level_to_components(
                lvl, supervisor=h.supervisor, batcher=h.batcher,
                engine=h.engine, store=h.store,
                clamp_new_tokens=self.clamp_new_tokens,
                evict_pages=self.ladder_evict_pages)

    # ---- wire-level overload (remote floor push, ISSUE 16) ----

    @staticmethod
    def _is_remote(h: ReplicaHandle) -> bool:
        return all(x is None for x in
                   (h.supervisor, h.batcher, h.engine, h.store))

    def _ctrl_channel(self, h: ReplicaHandle):
        ch = self._ctrl_chan_by_ep.get(h.endpoint)
        if ch is None:
            from brpc_tpu.rpc.channel import Channel
            # a dedicated short-timeout channel: a dead replica must
            # cost the tick loop control_timeout_ms, not the data
            # plane's full forward timeout
            ch = Channel(h.addr, timeout_ms=self.control_timeout_ms)
            self._ctrl_chan_by_ep[h.endpoint] = ch
        return ch

    def _push_floor(self, lvl: int) -> None:
        """Push the cluster gradient level (plus this router's
        membership epoch) to every REMOTE replica's ``_cluster``
        control service, and collect its pressure report from the
        reply.  A dropped push (injected ``cluster.floor_push``, dead
        replica) is simply re-pushed next tick; a replica that already
        saw a HIGHER epoch refuses — this router is superseded."""
        for h in self.replicas:
            if not self._is_remote(h):
                continue
            st = self._remote_floor.setdefault(h.endpoint, {
                "addr": h.addr, "epoch": self.epoch, "level": None,
                "acked_level": None, "last_push_t": None,
                "last_ack_t": None, "pressures": {}, "error": None,
                "unsupported": False, "drops": 0, "refused": 0})
            if st["unsupported"]:
                continue
            if fault.ENABLED and fault.hit(
                    "cluster.floor_push", replica=h.addr) is not None:
                # the push is LOST on the wire: no state change at the
                # replica; the next tick re-pushes
                st["drops"] += 1
                self.floor_push_drops += 1
                continue
            st["last_push_t"] = time.monotonic()
            st["level"] = lvl
            st["epoch"] = self.epoch
            self.floor_pushes += 1
            try:
                resp = self._ctrl_channel(h).call_sync(
                    "_cluster", "SetFloor",
                    {"epoch": int(self.epoch), "level": int(lvl),
                     "router": self.name},
                    serializer="tensorframe",
                    response_serializer="tensorframe")
            except errors.RpcError as e:
                if e.code == errors.ENOMETHOD:
                    # replica without the control service: stop asking
                    st["unsupported"] = True
                elif "stale epoch" in (e.text or ""):
                    st["refused"] += 1
                    self.floor_push_refused += 1
                st["error"] = e.code
                continue
            st["error"] = None
            st["last_ack_t"] = time.monotonic()
            st["acked_level"] = int((resp or {}).get("level", lvl))
            st["pressures"] = {
                k: float(resp[k]) for k in
                ("queue_delay_us", "pool_ratio", "queue_depth")
                if resp and k in resp}
            # the ack doubles as the replica's catalog publication
            # (ISSUE 18): fold its deployments into the fleet view
            rows = parse_deployments((resp or {}).get("deployments"))
            if rows is not None:
                self.catalog.note(h.addr, rows)

    def deploy_model(self, model: str, *, op: str = "deploy",
                     addrs: Optional[Sequence[str]] = None,
                     weight: int = 1,
                     state: Optional[str] = None) -> dict:
        """Fleet-wide lifecycle push (ISSUE 18): ``deploy`` /
        ``undeploy`` / ``drain`` one model on the named replicas (all
        by default), carrying this router's membership epoch so a
        superseded router's lifecycle pushes are fenced exactly like
        its floor pushes.  In-process replicas are driven directly;
        remote ones over the ``_cluster`` service.  Returns per-replica
        outcomes (``"ok"`` or the error text) — partial failure is the
        caller's to retry, the push is idempotent."""
        from brpc_tpu.serving.modelplane import cluster_deploy
        targets = []
        want = set(str(a) for a in addrs) if addrs is not None else None
        for h in self.replicas:
            if want is None or h.addr in want \
                    or str(h.endpoint) in (want or ()):
                targets.append(h)
        out = {}
        for h in targets:
            deps = getattr(h, "deployments", None)
            if deps is not None:
                if op == "deploy":
                    deps.deploy(model, weight=weight,
                                state=state or "loading")
                elif op == "undeploy":
                    deps.undeploy(model)
                elif op == "drain":
                    deps.drain(model)
                else:
                    raise ValueError(f"unknown deploy op {op!r}")
                self.catalog.note(h.addr, deps.snapshot())
                out[h.addr] = "ok"
                continue
            try:
                cluster_deploy(h.addr, epoch=self.epoch, model=model,
                               op=op, weight=weight, state=state,
                               timeout_ms=self.control_timeout_ms)
                out[h.addr] = "ok"
            except errors.RpcError as e:
                out[h.addr] = f"E{e.code}: {e.text}"
        return out

    def remote_floor_table(self) -> list[dict]:
        """Remote-floor propagation per replica for /cluster: epoch,
        last push, ack age, acked level."""
        now = time.monotonic()
        out = []
        for ep, st in list(self._remote_floor.items()):
            out.append({
                "addr": st["addr"], "epoch": st["epoch"],
                "pushed_level": st["level"],
                "acked_level": st["acked_level"],
                "push_age_s": (round(now - st["last_push_t"], 3)
                               if st["last_push_t"] else None),
                "ack_age_s": (round(now - st["last_ack_t"], 3)
                              if st["last_ack_t"] else None),
                "drops": st["drops"], "refused": st["refused"],
                "error": st["error"],
                "unsupported": st["unsupported"],
            })
        return out

    @property
    def level(self) -> int:
        return self._ladder.level

    # ---- lifecycle / introspection ----

    def close(self, timeout_s: float = 10.0) -> None:
        """Stop routing.  Running sessions are SUSPENDED (their records
        stay in the caller-owned SessionTable for the next router),
        replica-side gradient effects are undone, and this router's
        bvars are hidden."""
        self._running = False
        with self._ship_cv:
            self._ship_cv.notify_all()
        deadline = time.monotonic() + timeout_s
        with self._mu:
            drivers = list(self._drivers.values())
        for t in self._threads + drivers:
            t.join(max(0.0, deadline - time.monotonic()))
        self.sessions.suspend_running()
        # undo gradient side effects on caller-owned components
        self._ladder.reset()
        self._apply_level(0)
        for h in self.replicas:
            if h.supervisor is not None:
                h.supervisor.set_level_floor(0)
        from brpc_tpu.bvar.variable import find_exposed
        for n in self._bvar_names:
            v = find_exposed(n)
            if v is not None:
                v.hide()
        self.collector.close()

    def replica_table(self) -> list[dict]:
        from brpc_tpu.policy.circuit_breaker import global_breaker
        from brpc_tpu.policy.health_check import is_broken
        breaker = global_breaker()
        with self._mu:
            fail_counts = {ep: len(ts) for ep, ts in self._failures.items()}
        out = []
        for h in self.replicas:
            row = {
                "name": h.name,
                "addr": h.addr,
                "healthy": not is_broken(h.endpoint),
                "quarantined": is_broken(h.endpoint),
                "breaker_isolations": breaker.isolation_count(h.endpoint),
                "recent_failures": fail_counts.get(h.endpoint, 0),
                "local": any(x is not None for x in
                             (h.supervisor, h.batcher, h.engine, h.store)),
            }
            if h.supervisor is not None:
                row["ladder_level"] = h.supervisor.level
                row["state"] = h.supervisor.state
            out.append(row)
        return out

    def stats(self) -> dict:
        wal = self.sessions.wal
        return {
            "name": self.name,
            "epoch": self.epoch,
            "replicas": self.replica_table(),
            "sessions": self.sessions.counts(),
            "sessions_by_model": self.sessions.counts_by_model(),
            "session_rows": self.sessions.snapshot(limit=20),
            "ladder": self._ladder.stats(),
            "level_actions": list(LEVEL_ACTIONS),
            "gradient_fired": {a: c.get_value()
                               for a, c in self.gradient_fired.items()},
            "shed": self.shed_total.get_value(),
            "forwards": self.forwards.get_value(),
            "resumes": self.resumes_total.get_value(),
            "reconnects": self.reconnects.get_value(),
            "replayed_tokens": self.replays_total.get_value(),
            "retry_after_s": self.retry_after_s(),
            "replicate_sessions": self.replicate_sessions,
            "replication_factor": self.replication_factor,
            "placements": self.placements(),
            "default_model": self.default_model,
            "catalog": self.catalog.snapshot(),
            "models": self.model_metrics.snapshot(),
            "canary": self.canary.snapshot(),
            "wrong_model_routes": self.wrong_model_routes.get_value(),
            "telemetry": self.collector.stats(),
            "slo": self.slo.snapshot() if self.slo is not None else None,
            "remote_floor": self.remote_floor_table(),
            "floor_pushes": self.floor_pushes,
            "floor_push_drops": self.floor_push_drops,
            "floor_push_refused": self.floor_push_refused,
            "wal": wal.stats() if wal is not None else None,
            "wal_replay": self.sessions.replay_stats,
        }


class RouterService(Service):
    """RPC surface of a ClusterRouter: streaming ``Generate`` (fresh
    session) and ``Resume`` (reconnect with ``session_id`` + cursor).
    Token messages carry the cursor — ``{"token": t, "cursor": i}`` —
    so clients can checkpoint without counting."""

    NAME = ROUTER_SERVICE

    def __init__(self, router: ClusterRouter):
        self._router = router

    def _attach_stream(self, cntl, sess_or_sid, cursor: int):
        router = self._router
        stream = cntl.accept_stream()
        state = {"cursor": cursor}

        def emit(tok: int) -> None:
            state["cursor"] += 1
            stream.write(json.dumps(
                {"token": int(tok),
                 "cursor": state["cursor"]}).encode(), timeout_s=2.0)

        def on_done(err) -> None:
            msg = {"done": True, "session_id": sid}
            if err is not None:
                msg["error"] = err.code
                msg["error_text"] = err.text
            try:
                stream.write(json.dumps(msg).encode(), timeout_s=2.0)
            except errors.RpcError:
                pass
            stream.close()

        if isinstance(sess_or_sid, Session):
            sid = sess_or_sid.sid
            info = router.attach(sid, cursor, emit, on_done)
        else:
            sid = str(sess_or_sid)
            info = router.attach(sid, cursor, emit, on_done)
        return sid, info

    @method(request="json", response="json")
    def Generate(self, cntl, req):
        req = req or {}
        prompt = req.get("prompt") or [0]
        max_new = int(req.get("max_new_tokens", 16))
        model = req.get("model") or None
        try:
            sess = self._router.open_session(prompt, max_new, model=model)
        except errors.RpcError as e:
            cntl.set_failed(e.code, e.text)    # ELIMIT retry_after_s=<hint>
            return None                        # or EREQUEST unknown model
        try:
            sid, _ = self._attach_stream(cntl, sess, 0)
        except errors.RpcError as e:
            # the client never learned the session_id: an admitted-but-
            # unattachable session would decode its whole budget for
            # nobody — cancel it
            self._router.cancel_session(sess, e)
            cntl.set_failed(e.code, e.text)
            return None
        return {"accepted": True, "session_id": sid,
                "model": sess.model}

    @method(request="json", response="json")
    def Resume(self, cntl, req):
        req = req or {}
        sid = req.get("session_id")
        if not sid:
            cntl.set_failed(errors.EREQUEST, 'missing "session_id"')
            return None
        cursor = int(req.get("cursor", 0))
        try:
            sid, info = self._attach_stream(cntl, str(sid), cursor)
        except errors.RpcError as e:
            cntl.set_failed(e.code, e.text)
            return None
        return {"accepted": True, "session_id": sid, **info}

    @method(request="json", response="json")
    def Stats(self, cntl, req):
        return self._router.stats()


def register_router(server, router: ClusterRouter) -> RouterService:
    """Expose `router` on `server` (call before ``server.start()``).
    The router process joins the fleet telemetry plane too (ISSUE 20):
    its ``_telemetry`` service is what lets ANOTHER router (or an
    operator's one-shot pull) read this one's bvars and spans."""
    from brpc_tpu.serving.telemetry import (TELEMETRY_SERVICE,
                                            register_telemetry)
    svc = RouterService(router)
    server.add_service(svc)
    if TELEMETRY_SERVICE not in server.services:
        register_telemetry(server, name=router.name)
    return svc


# ---------------------------------------------------------------------------
# client side
# ---------------------------------------------------------------------------

class _ClientCollector:
    """Client stream handler: tokens + cursors + the terminal, with the
    session_id latched from the done message."""

    def __init__(self, emit: Optional[Callable[[int], None]] = None):
        self.tokens: list[int] = []
        self.cursor = 0
        self.session_id: Optional[str] = None
        self.error: Optional[int] = None
        self.done = threading.Event()
        self._emit = emit
        self._terminal_seen = False

    def on_received_messages(self, stream, messages):
        for m in messages:
            try:
                d = json.loads(m)
            except ValueError:
                continue
            if "token" in d:
                t = int(d["token"])
                self.tokens.append(t)
                self.cursor = int(d.get("cursor", self.cursor + 1))
                if self._emit is not None:
                    self._emit(t)
            if d.get("done"):
                self._terminal_seen = True
                if d.get("session_id"):
                    self.session_id = str(d["session_id"])
                if d.get("error"):
                    self.error = int(d["error"])
                self.done.set()

    def on_closed(self, stream):
        if not self._terminal_seen:
            self.error = errors.EFAILEDSOCKET
        self.done.set()

    def on_idle_timeout(self, stream):
        pass


class LiveGeneration:
    """One in-flight client-side generation: collects tokens, exposes
    the cursor, and can DROP the connection mid-stream (the client-
    failure half of the chaos scenario)."""

    def __init__(self, session_id: str, collector: _ClientCollector,
                 stream):
        self.session_id = session_id
        self._col = collector
        self._stream = stream

    @property
    def tokens(self) -> list[int]:
        return list(self._col.tokens)

    @property
    def cursor(self) -> int:
        return self._col.cursor

    @property
    def error(self) -> Optional[int]:
        return self._col.error

    def wait(self, timeout_s: float = 30.0) -> bool:
        return self._col.done.wait(timeout_s)

    def wait_tokens(self, n: int, timeout_s: float = 10.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if len(self._col.tokens) >= n or self._col.done.is_set():
                return len(self._col.tokens) >= n
            time.sleep(0.005)
        return False

    def drop(self) -> None:
        """Simulate the client dying: close the stream.  The session
        keeps decoding server-side; reconnect with ``session_id`` +
        ``cursor`` to resume."""
        try:
            self._stream.close()
        except Exception:
            pass
        self._col.done.set()


_RETRY_AFTER_RE = re.compile(r"retry_after_s=([0-9]+(?:\.[0-9]+)?)")


def parse_retry_after_s(text: str | None) -> Optional[float]:
    """The ``retry_after_s=<x>`` hint a router shed attaches to its
    ELIMIT text, or None when the error carries no hint."""
    if not text:
        return None
    m = _RETRY_AFTER_RE.search(text)
    return float(m.group(1)) if m else None


class RouterClient:
    """Thin client for the Router service: ``generate`` (blocking),
    ``start`` (live handle with ``drop()``), ``resume`` (reconnect).

    ROADMAP 3(c): a router shed (ELIMIT carrying a ``retry_after_s``
    hint) is no longer just a text hint — ``start``/``generate`` back
    off for the HINTED delay (plus bounded jitter so a shed burst's
    clients don't re-arrive in lockstep) and retry, up to
    ``shed_retries`` attempts.  An ELIMIT without a hint, any other
    error, or an exhausted budget surfaces to the caller unchanged,
    and backoff sleeps count against the caller's deadline
    (``generate(timeout_s=...)`` / ``start(deadline_s=...)``): a
    retry whose delay would overshoot it surfaces the shed
    immediately instead of sleeping past the budget.  Set
    ``shed_retries=0`` to restore the raw single-attempt
    behavior."""

    def __init__(self, addr: str, *, timeout_ms: int = 10_000,
                 shed_retries: int = 3, max_backoff_s: float = 30.0,
                 jitter_frac: float = 0.1):
        from brpc_tpu.rpc.channel import Channel
        self.addr = addr
        self.timeout_ms = int(timeout_ms)
        self.shed_retries = int(shed_retries)
        self.max_backoff_s = float(max_backoff_s)
        self.jitter_frac = float(jitter_frac)
        # observability for callers/tests: every backoff this client
        # actually slept, as (hinted_s, slept_s)
        self.backoffs: list = []
        self._ch = Channel(addr, timeout_ms=self.timeout_ms)

    def _shed_backoff_s(self, hint_s: float) -> float:
        import random
        jitter = random.uniform(0.0, self.jitter_frac * hint_s)
        return min(hint_s + jitter, self.max_backoff_s)

    def start(self, prompt: Sequence[int], max_new_tokens: int, *,
              emit: Optional[Callable[[int], None]] = None,
              deadline_s: Optional[float] = None,
              model: Optional[str] = None) -> LiveGeneration:
        attempt = 0
        deadline = (time.monotonic() + deadline_s) \
            if deadline_s is not None else None
        while True:
            try:
                return self._start_once(prompt, max_new_tokens,
                                        emit=emit, model=model)
            except errors.RpcError as e:
                hint = parse_retry_after_s(e.text) \
                    if e.code == errors.ELIMIT else None
                if hint is None or attempt >= self.shed_retries:
                    raise
                delay = self._shed_backoff_s(hint)
                if deadline is not None and \
                        time.monotonic() + delay > deadline:
                    # honoring the hint would overshoot the caller's
                    # budget: surface the shed now instead of sleeping
                    # past the deadline
                    raise
                attempt += 1
                self.backoffs.append((hint, delay))
                # honor the hint: earlier re-arrival would land inside
                # the same overload plateau and be shed again
                time.sleep(delay)

    def _start_once(self, prompt: Sequence[int], max_new_tokens: int, *,
                    emit: Optional[Callable[[int], None]] = None,
                    model: Optional[str] = None) -> LiveGeneration:
        from brpc_tpu.rpc.controller import Controller
        from brpc_tpu.rpc.stream import stream_create
        col = _ClientCollector(emit)
        cntl = Controller(timeout_ms=self.timeout_ms)
        stream = stream_create(cntl, col)
        req = {"prompt": [int(t) for t in prompt],
               "max_new_tokens": int(max_new_tokens)}
        if model:
            req["model"] = str(model)
        try:
            resp = self._ch.call_sync(
                "Router", "Generate", req,
                serializer="json", cntl=cntl)
        except errors.RpcError:
            # shed (ELIMIT) or dead router: the never-bound stream
            # must close or it leaks in the StreamRegistry
            try:
                stream.close()
            except Exception:
                pass
            raise
        sid = str((resp or {}).get("session_id", ""))
        return LiveGeneration(sid, col, stream)

    def generate(self, prompt: Sequence[int], max_new_tokens: int, *,
                 emit: Optional[Callable[[int], None]] = None,
                 timeout_s: float = 30.0,
                 model: Optional[str] = None) -> dict:
        deadline = time.monotonic() + timeout_s
        gen = self.start(prompt, max_new_tokens, emit=emit,
                         deadline_s=timeout_s, model=model)
        if not gen.wait(max(0.0, deadline - time.monotonic())):
            raise errors.RpcError(errors.ERPCTIMEDOUT,
                                  "router generation never finished")
        return {"session_id": gen.session_id, "tokens": gen.tokens,
                "cursor": gen.cursor, "error": gen.error}

    def resume(self, session_id: str, cursor: int, *,
               emit: Optional[Callable[[int], None]] = None
               ) -> LiveGeneration:
        from brpc_tpu.rpc.controller import Controller
        from brpc_tpu.rpc.stream import stream_create
        col = _ClientCollector(emit)
        col.cursor = int(cursor)
        cntl = Controller(timeout_ms=self.timeout_ms)
        stream = stream_create(cntl, col)
        try:
            self._ch.call_sync(
                "Router", "Resume",
                {"session_id": str(session_id), "cursor": int(cursor)},
                serializer="json", cntl=cntl)
        except errors.RpcError:
            try:
                stream.close()
            except Exception:
                pass
            raise
        return LiveGeneration(str(session_id), col, stream)

    def resume_wait(self, session_id: str, cursor: int, *,
                    timeout_s: float = 30.0) -> dict:
        gen = self.resume(session_id, cursor)
        if not gen.wait(timeout_s):
            raise errors.RpcError(errors.ERPCTIMEDOUT,
                                  "router resume never finished")
        return {"session_id": session_id, "tokens": gen.tokens,
                "cursor": gen.cursor, "error": gen.error}
