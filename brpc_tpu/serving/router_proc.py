"""Subprocess cluster-router entry point (ISSUE 16).

The durable-session story only means something across a PROCESS death:
chaos scenario 14 killed replica engines, but the router itself — the
thing holding every session — was always the test process.  This
module is the missing half: a ``ClusterRouter`` runnable as its own
OS process over remote-only replicas, adopting (or creating) a session
WAL, so a harness can ``SIGKILL`` it mid-generation and spin up a
successor over the same WAL file:

    python -m brpc_tpu.serving.router_proc '{"wal": ..., "replicas":
        [...], ...}'

The child prints ``ROUTER_PORT <port>`` on stdout once serving, then
blocks until stdin closes (the parent's handle going away doubles as
the shutdown signal, so an orphaned router never outlives its
harness).  :func:`spawn_router` wraps the Popen + port handshake for
the press tool and tests.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Optional, Sequence


def run_router(cfg: dict):
    """Build and serve a router from a config dict (see main()); blocks
    until stdin closes.  Factored out of main() so a test can drive the
    same path in-process."""
    import brpc_tpu as brpc
    from brpc_tpu.serving.router import (ClusterRouter, SessionTable,
                                         register_router)

    wal_path = cfg.get("wal")
    sessions: Optional[SessionTable] = None
    if wal_path and os.path.exists(wal_path):
        sessions = SessionTable.recover(
            wal_path, keep_finished=int(cfg.get("keep_finished", 512)))
    router = ClusterRouter(
        list(cfg["replicas"]),
        sessions=sessions,
        wal=(wal_path if sessions is None else None),
        max_sessions=int(cfg.get("max_sessions", 256)),
        check_interval_s=float(cfg.get("check_interval_s", 0.05)),
        replicate_sessions=bool(cfg.get("replicate_sessions", True)),
        replication_factor=int(cfg.get("replication_factor", 2)),
        page_tokens=int(cfg.get("page_tokens", 8)),
        progress_timeout_s=float(cfg.get("progress_timeout_s", 30.0)),
        name=str(cfg.get("name", "router_proc")),
        timeout_ms=int(cfg.get("timeout_ms", 20_000)))
    srv = brpc.Server()
    register_router(srv, router)
    srv.start(cfg.get("host", "127.0.0.1"), int(cfg.get("port", 0)))
    return router, srv


def main(argv: Sequence[str]) -> int:
    cfg = json.loads(argv[1]) if len(argv) > 1 else {}
    router, srv = run_router(cfg)
    print(f"ROUTER_PORT {srv.port}", flush=True)
    try:
        # block until the parent closes our stdin (or kills us — the
        # whole point of this process is being killable)
        while sys.stdin.readline():
            pass
    except KeyboardInterrupt:
        pass
    router.close(timeout_s=2.0)
    srv.stop()
    srv.join()
    return 0


def spawn_router(wal_path: str, replica_addrs: Sequence[str], *,
                 timeout_s: float = 20.0, **cfg):
    """Launch a router subprocess over `wal_path` + remote replicas;
    returns ``(proc, addr)`` once the child reports its port.  Kill it
    with ``proc.kill()`` (SIGKILL — no goodbye, that's the test) and
    spawn a successor over the same ``wal_path`` to adopt the fleet."""
    cfg = dict(cfg)
    cfg["wal"] = str(wal_path)
    cfg["replicas"] = [str(a) for a in replica_addrs]
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    proc = subprocess.Popen(
        [sys.executable, "-m", "brpc_tpu.serving.router_proc",
         json.dumps(cfg)],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, env=env, cwd=repo_root, text=True)
    deadline = time.monotonic() + timeout_s
    line = ""
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if line.startswith("ROUTER_PORT "):
            port = int(line.split()[1])
            return proc, f"127.0.0.1:{port}"
        if not line and proc.poll() is not None:
            break
    proc.kill()
    raise RuntimeError(
        f"router subprocess never reported a port (last line: {line!r})")


if __name__ == "__main__":
    sys.exit(main(sys.argv))
