"""Serving registration glue — exposes the batcher and the decode engine
on an ordinary rpc/server.py.

Two methods ride the normal dispatch path (auth, interceptor, limiters,
MethodStatus accounting all apply):

  * ``Serving.Score`` — unary, JSON ``{"x": [floats...]}``; the handler
    defers the RPC into the DynamicBatcher and the batch drainer
    completes it (``{"y": ...}``), ELIMIT-shedding deadline-doomed
    requests up front.
  * ``Serving.Generate`` — streaming, JSON ``{"prompt": [ints...],
    "max_new_tokens": N}`` with a client stream attached
    (``stream_create``); each generated token arrives as one stream
    message ``{"token": t}``, terminated by ``{"done": true}`` and
    stream close.

HTTP clients get the same decode stream without a TRPC stack:
``/serving/generate?prompt=1,2,3&max_new_tokens=8`` answers chunked
(ProgressiveAttachment), one JSON line per token.
"""
from __future__ import annotations

import json
from typing import Optional

import numpy as np

from brpc_tpu import errors
from brpc_tpu.rpc.service import Service, method


class ServingService(Service):
    NAME = "Serving"

    def __init__(self, batcher=None, engine=None, prefix_fetcher=None,
                 deployments=None):
        self._batcher = batcher
        self._engine = engine
        # pull-based prefix fetch (ISSUE 16): ``fetch(prompt, holders)
        # -> pages`` — usually brpc_tpu.migrate.make_prefix_fetcher.
        # Set after server start too (the fetcher needs its own addr).
        self.prefix_fetcher = prefix_fetcher
        self.prefix_fetches = 0
        self.prefix_fetched_pages = 0
        # multi-model plane (ISSUE 18): a ReplicaDeployments table maps
        # the forwarded "model" field to per-deployment bindings.  None
        # keeps the legacy single-anonymous-model behavior exactly.
        self.deployments = deployments
        self.n_model_misroutes = 0

    def _resolve(self, cntl, req):
        """``(model_key, bindings)`` for this request.  Without a
        deployment table the constructor bindings apply (model field
        ignored — a pre-plane replica).  A forwarded model this replica
        does not serve fails EINTERNAL — a FAILOVER code, so the
        router's session driver re-routes instead of killing the
        session — and bumps ``n_model_misroutes`` (must stay 0 in a
        healthy fleet: the router constrains picks to the catalog)."""
        model = (req or {}).get("model") or None
        if self.deployments is None or len(self.deployments) == 0:
            return None, {"engine": self._engine,
                          "batcher": self._batcher,
                          "prefix_fetcher": self.prefix_fetcher}
        try:
            key, row = self.deployments.resolve(model)
        except KeyError:
            self.n_model_misroutes += 1
            cntl.set_failed(
                errors.EINTERNAL,
                f"model {model!r} not served by this replica "
                f"(serves {self.deployments.keys()})")
            return None, None
        return key, {"engine": row.get("engine") or self._engine,
                     "batcher": row.get("batcher") or self._batcher,
                     "prefix_fetcher": (row.get("prefix_fetcher")
                                        or self.prefix_fetcher)}

    @method(request="json", response="json")
    def Score(self, cntl, req):
        _, b = self._resolve(cntl, req)
        if b is None:
            return None
        batcher = b["batcher"]
        if batcher is None:
            cntl.set_failed(errors.ENOMETHOD, "no batcher registered")
            return None
        x = (req or {}).get("x")
        if x is None:
            cntl.set_failed(errors.EREQUEST, 'missing "x"')
            return None
        batcher.submit(
            cntl, np.asarray(x, dtype=np.float32),
            transform=lambda row: {"y": np.asarray(row).tolist()})
        return None   # deferred: the batch drainer completes the RPC

    @method(request="tensorframe", response="tensorframe")
    def ScoreT(self, cntl, req):
        """Score on the BINARY tensor wire (ISSUE 17 adopter): the row
        payload rides as a float32 tensor field both ways — no float
        list round-trip.  Old peers never see this; new clients
        (:class:`ScoreClient`) downgrade sticky on ENOMETHOD."""
        _, b = self._resolve(cntl, req)
        if b is None:
            return None
        batcher = b["batcher"]
        if batcher is None:
            cntl.set_failed(errors.ENOMETHOD, "no batcher registered")
            return None
        x = (req or {}).get("x")
        if not isinstance(x, np.ndarray) or x.ndim != 1:
            cntl.set_failed(errors.EREQUEST,
                            'need rank-1 tensor field "x"')
            return None
        batcher.submit(
            cntl, np.asarray(x, dtype=np.float32),
            transform=lambda row: {"y": np.asarray(row, np.float32)})
        return None   # deferred: the batch drainer completes the RPC

    @method(request="json", response="json")
    def Generate(self, cntl, req):
        model_key, b = self._resolve(cntl, req)
        if b is None:
            return None
        engine = b["engine"]
        if engine is None:
            cntl.set_failed(errors.ENOMETHOD, "no decode engine registered")
            return None
        req = req or {}
        prompt = req.get("prompt") or [0]
        max_new = int(req.get("max_new_tokens", 16))
        stream = cntl.accept_stream()

        def emit(tok: int) -> None:
            # emit runs on THIS request's emitter thread (the engine's
            # per-request bounded emit buffer), so a consumer that
            # stops draining its credit window stalls only itself: the
            # shared step loop keeps decoding every other slot, and
            # once this request's buffer overflows the engine cuts it
            # with EOVERCROWDED.  The bounded write keeps the emitter
            # itself from wedging forever on a dead-but-open peer.
            stream.write(json.dumps({"token": tok}).encode(),
                         timeout_s=2.0)

        def on_done(err) -> None:
            if err is None and model_key is not None \
                    and self.deployments is not None:
                # warm-up proof: a completed generation flips this
                # deployment loading -> warm on the published plane
                self.deployments.note_generation(model_key)
            msg = {"done": True}
            if err is not None:
                msg["error"] = err.code
                msg["error_text"] = err.text
            try:
                # same bound as emit (also on the per-request emitter
                # thread, after the buffered tokens flush)
                stream.write(json.dumps(msg).encode(), timeout_s=2.0)
            except errors.RpcError:
                pass   # peer already gone; nothing to tell it
            stream.close()

        # advisory prefix probe BEFORE submit: how many prompt tokens
        # the local KV cache can serve without re-decoding.  The
        # cluster router's resume path reads this to account the
        # re-decoded-token cost of a failover (ISSUE 8) — a resume that
        # lands on a replica holding the committed prefix reports
        # prefix_hit > 0 and re-prefills only the tail.
        hit = 0
        store = getattr(engine, "store", None)
        if store is not None and len(prompt) > 1:
            try:
                hit = int(store.probe(prompt))
            except Exception:
                hit = 0
        # pull-based prefix fetch (ISSUE 16): when the router names
        # replicas that hold this prefix (prefix_holders) and the local
        # cache misses the full-page prefix, FETCH it from an owner via
        # the migrator before submitting — a cold replica warms itself
        # instead of re-prefilling.  Any fetch failure falls back to
        # recompute; the generation never depends on it.
        holders = req.get("prefix_holders") or []
        fetcher = b["prefix_fetcher"]
        if (fetcher is not None and holders
                and store is not None and len(prompt) > 1):
            pt = getattr(store, "page_tokens", 16)
            full = len(prompt) // pt * pt
            if full and hit < full:
                try:
                    fetched = int(fetcher(
                        [int(t) for t in prompt],
                        [str(h) for h in holders]))
                except Exception:
                    fetched = 0
                if fetched:
                    self.prefix_fetches += 1
                    self.prefix_fetched_pages += fetched
                    try:
                        hit = max(hit, int(store.probe(prompt)))
                    except Exception:
                        pass
        kw = {}
        if "speculative" in req:
            # per-request opt-out of the engine's draft proposals
            # (ISSUE 11); only forwarded when the client says so, so
            # engine-shaped submitters without the keyword still work
            kw["speculative"] = bool(req["speculative"])
        rid = engine.submit(prompt, max_new, emit, on_done, **kw)
        resp = {"accepted": True, "req_id": rid, "prefix_hit": hit}
        if model_key is not None:
            resp["model"] = model_key
        return resp


class ScoreClient:
    """Client half of the Score adopter (ISSUE 17): prefers the binary
    ``ScoreT`` wire and downgrades STICKY to json ``Score`` when the
    peer answers ENOMETHOD (an old server) — the per-peer negotiation
    contract the PS client runs per shard.  Both paths return the same
    float32 rows; the regression test pins them byte-identical."""

    def __init__(self, channel):
        self._ch = channel
        self._mode: Optional[str] = None     # None | "frame" | "json"
        self.n_negotiation_fallbacks = 0

    @property
    def wire_mode(self) -> Optional[str]:
        return self._mode

    def score(self, x, **kw) -> np.ndarray:
        x = np.ascontiguousarray(x, dtype=np.float32)
        if self._mode != "json":
            try:
                resp = self._ch.call_sync(
                    "Serving", "ScoreT", {"x": x},
                    serializer="tensorframe", **kw)
                self._mode = "frame"
                return np.asarray(resp["y"], np.float32)
            except errors.RpcError as e:
                if e.code != errors.ENOMETHOD:
                    raise
                self._mode = "json"
                self.n_negotiation_fallbacks += 1
        resp = self._ch.call_sync("Serving", "Score",
                                  {"x": x.tolist()},
                                  serializer="json", **kw)
        return np.asarray(resp["y"], np.float32)


def http_generate_handler(engine):
    """Build an HTTP handler streaming decode tokens as chunked JSON
    lines through a ProgressiveAttachment — the no-TRPC client path."""
    from brpc_tpu.rpc.progressive import ProgressiveResponse

    def handler(req):
        try:
            prompt = [int(t) for t in
                      (req.query.get("prompt") or "0").split(",") if t]
            max_new = int(req.query.get("max_new_tokens", "16"))
        except ValueError as e:
            from brpc_tpu.builtin.router import http_response
            return http_response(400, f"bad query: {e}\n")

        def writer(pa):
            def emit(tok: int) -> None:
                # ProgressiveAttachment.write returns -1 (never raises)
                # once the connection died; raising here makes the
                # engine retire the slot instead of decoding to nobody
                if pa.write(json.dumps({"token": tok}) + "\n") != 0:
                    raise errors.RpcError(errors.EFAILEDSOCKET,
                                          "http client gone")

            def on_done(err) -> None:
                msg = {"done": True}
                if err is not None:
                    msg["error"] = err.code
                pa.write(json.dumps(msg) + "\n")
                pa.close()

            engine.submit(prompt, max_new, emit, on_done)

        return ProgressiveResponse(writer,
                                   content_type="application/json-seq")

    return handler


def register_serving(server, batcher=None, engine=None,
                     prefix_fetcher=None, deployments=None,
                     http_generate_path: Optional[str]
                     = "/serving/generate") -> ServingService:
    """Register the serving surface on a Server: the Serving service
    (Score/Generate) plus the chunked HTTP generate route.  Call before
    ``server.start()``."""
    svc = ServingService(batcher, engine, prefix_fetcher,
                         deployments=deployments)
    server.add_service(svc)
    if engine is not None and http_generate_path:
        server.add_http_handler(http_generate_path,
                                http_generate_handler(engine))
    return svc
