"""Session write-ahead log — durable cluster control plane (ISSUE 16).

PR 8's SessionTable made sessions survive a router OBJECT restart: the
table is caller-owned, so a new :class:`~brpc_tpu.serving.router.
ClusterRouter` over the same table adopts every in-flight generation.
But the table was RAM: a router PROCESS crash lost every record, and
"RPC Considered Harmful" (PAPERS.md) is explicit that long-lived
serving state must outlive any single transport endpoint — including
the coordinator's own process.  This module is the durability layer:

  * every session mutation — ``open`` (create), ``tok`` (one
    cursor-advance), ``fin`` (terminal), ``ep`` (membership epoch) —
    is appended as one checksummed :mod:`~brpc_tpu.butil.recordio`
    record and flushed BEFORE the token reaches any client sink.  The
    write-ahead discipline is the same as the session record's own
    (PR 8) and :class:`~brpc_tpu.migrate.StandbySync`'s: the durable
    record is a superset of any client-visible view, so a successor
    process replaying the WAL can never be BEHIND a cursor some client
    will present.  (Flush-to-OS suffices for the process-death model;
    pass ``fsync=True`` to survive machine death too.)

  * an append failure (disk error, injected ``router.wal_append``)
    NEVER touches the token path: the un-durable record parks on a
    pending tail that self-heals by riding the next successful append,
    order preserved.  A crash inside the gap degrades that session to
    recompute-on-resume — the successor's record is shorter than the
    client's cursor, the driver re-decodes the missing tail bit-exact,
    and delivery is suppressed up to the cursor — never a duplicate
    token (tests/test_chaos.py scenario 17).

  * COMPACTION is bounded and background: once the log grows past
    ``compact_bytes``/``compact_min_records``, a snapshot of the live
    table (one ``snap`` record per session, provided by the owning
    SessionTable via ``snapshot_source``) replaces the history through
    an atomic rename.  Replay cost is bounded by table size, not by
    tokens ever decoded.

  * OPENING IS RECOVERING: the constructor replays whatever the path
    holds (corrupt records skipped by recordio's resync, a truncated
    tail loses only itself) into ``recovered`` + ``replay`` stats, and
    ``SessionTable.recover(path)`` turns that into live Session
    objects.  The max ``ep`` record seen is the fleet's membership
    epoch; a successor bumps it so replicas can fence floor pushes
    from the superseded router (serving/cluster_control.py).
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Callable, Optional

from brpc_tpu import fault
from brpc_tpu.butil.lockprof import InstrumentedLock
from brpc_tpu.butil.recordio import RecordReader, RecordWriter
from brpc_tpu.serving.modelplane import DEFAULT_MODEL

# record type tags (recordio meta field)
REC_OPEN = b"open"
REC_TOK = b"tok"
REC_FIN = b"fin"
REC_SNAP = b"snap"
REC_EPOCH = b"ep"


class SessionWAL:
    """Write-ahead log for one SessionTable (see module docstring).

    ``recovered`` holds ``{sid: {"prompt", "budget", "emitted",
    "state", "error_code", "model"}}`` replayed from the path at open;
    ``SessionTable.recover`` consumes (and clears) it.  All ``append_*``
    methods are non-raising: failures park on the pending tail and are
    counted, because the WAL must never break the token path it
    guards."""

    def __init__(self, path, *, compact_bytes: int = 1 << 20,
                 compact_min_records: int = 2048, fsync: bool = False,
                 auto_compact: bool = True):
        self.path = str(path)
        self.compact_bytes = int(compact_bytes)
        self.compact_min_records = int(compact_min_records)
        self.fsync = bool(fsync)
        self._mu = InstrumentedLock("router.wal")
        # snapshot provider for compaction — set by the owning
        # SessionTable (returns the session dicts a snap record holds)
        self.snapshot_source: Optional[Callable[[], list]] = None

        self.epoch = 0
        self.records = 0            # records in the file right now
        self.appends = 0
        self.append_failures = 0
        self.healed_records = 0     # pending-tail records later durably written
        self.compactions = 0
        self.last_compaction: Optional[dict] = None
        self._pending: deque = deque()   # (meta, body) not yet durable

        self.recovered: dict[str, dict] = {}
        self.replay = self._replay()

        self._fp = open(self.path, "ab")
        self._writer = RecordWriter(self._fp)

        self._closed = False
        self._compact_cv = threading.Condition(self._mu)
        self._compact_thread: Optional[threading.Thread] = None
        if auto_compact:
            t = threading.Thread(target=self._compact_loop, daemon=True,
                                 name="session-wal-compact")
            t.start()
            self._compact_thread = t

    # ---- replay (open IS recover) ----

    def _replay(self) -> dict:
        t0 = time.monotonic()
        stats = {"records": 0, "sessions": 0, "orphan_tok": 0,
                 "gap_tok": 0, "epoch": 0, "replay_ms": 0.0,
                 "bytes": 0}
        if not os.path.exists(self.path):
            return stats
        stats["bytes"] = os.path.getsize(self.path)
        sessions: dict[str, dict] = {}
        with open(self.path, "rb") as fp:
            for meta, body in RecordReader(fp):
                stats["records"] += 1
                try:
                    d = json.loads(body)
                except ValueError:
                    continue
                if meta == REC_EPOCH:
                    stats["epoch"] = max(stats["epoch"],
                                         int(d.get("e", 0)))
                elif meta == REC_OPEN and d["s"] not in sessions:
                    # never clobbers an existing record: a compaction
                    # snapshot supersedes any healed-late open record.
                    # "m" is the model column (ISSUE 18); records from
                    # before the multi-model plane lack it and decode
                    # as the default model — version-tolerant decode.
                    sessions[d["s"]] = {
                        "prompt": [int(t) for t in d.get("p", [])],
                        "budget": int(d.get("b", 0)),
                        "emitted": [], "state": "running",
                        "error_code": None,
                        "model": str(d.get("m") or DEFAULT_MODEL)}
                elif meta == REC_SNAP:
                    sessions[d["s"]] = {
                        "prompt": [int(t) for t in d.get("p", [])],
                        "budget": int(d.get("b", 0)),
                        "emitted": [int(t) for t in d.get("e", [])],
                        "state": str(d.get("st", "running")),
                        "error_code": (None if d.get("ec") is None
                                       else int(d["ec"])),
                        "model": str(d.get("m") or DEFAULT_MODEL)}
                elif meta == REC_TOK:
                    rec = sessions.get(d["s"])
                    if rec is None:
                        stats["orphan_tok"] += 1
                        continue
                    cur = int(d.get("c", 0))
                    have = len(rec["emitted"])
                    if cur == have + 1:
                        rec["emitted"].append(int(d["t"]))
                    elif cur > have + 1:
                        # a lost record left a hole: everything past it
                        # is unplaceable — the resume re-decodes the
                        # tail instead (never serves a gapped record)
                        stats["gap_tok"] += 1
                    # cur <= have: duplicate from a healed tail; ignore
                elif meta == REC_FIN:
                    rec = sessions.get(d["s"])
                    if rec is not None:
                        code = (None if d.get("ec") is None
                                else int(d["ec"]))
                        rec["state"] = "failed" if code else "finished"
                        rec["error_code"] = code
        stats["sessions"] = len(sessions)
        stats["replay_ms"] = round((time.monotonic() - t0) * 1e3, 3)
        self.recovered = sessions
        self.epoch = stats["epoch"]
        self.records = stats["records"]
        return stats

    # ---- appends (write-ahead, non-raising) ----

    def _write_locked(self, meta: bytes, body: bytes) -> None:
        self._writer.write(body, meta)
        self._writer.flush()
        if self.fsync:
            os.fsync(self._fp.fileno())
        self.records += 1

    def _append(self, meta: bytes, body: dict) -> bool:
        """Append one record, draining the pending (un-durable) tail
        first so record order is preserved across failures.  Returns
        True when THIS record reached the file."""
        raw = json.dumps(body, separators=(",", ":")).encode()
        with self._mu:
            if self._closed:
                return False
            self.appends += 1
            if (fault.ENABLED and
                    fault.hit("router.wal_append",
                              path=self.path) is not None):
                self.append_failures += 1
                self._pending.append((meta, raw))
                return False
            try:
                while self._pending:
                    pm, pb = self._pending[0]
                    self._write_locked(pm, pb)
                    self._pending.popleft()
                    self.healed_records += 1
                self._write_locked(meta, raw)
            except OSError:
                self.append_failures += 1
                self._pending.append((meta, raw))
                return False
            if self.records >= self.compact_min_records:
                self._compact_cv.notify()
            return True

    def append_open(self, sid: str, prompt, budget: int,
                    model: Optional[str] = None) -> bool:
        body = {"s": sid, "p": [int(t) for t in prompt],
                "b": int(budget)}
        # the model column rides only when it says something: default-
        # model records stay byte-identical to pre-plane WALs (and old
        # readers ignore unknown keys anyway)
        if model and model != DEFAULT_MODEL:
            body["m"] = str(model)
        return self._append(REC_OPEN, body)

    def append_tok(self, sid: str, tok: int, cursor: int) -> bool:
        return self._append(REC_TOK,
                            {"s": sid, "c": int(cursor), "t": int(tok)})

    def append_fin(self, sid: str, error_code=None) -> bool:
        ec = None if error_code is None else int(error_code)
        return self._append(REC_FIN, {"s": sid, "ec": ec})

    def bump_epoch(self) -> int:
        """Advance the fleet membership epoch and persist it — called
        by a router ADOPTING this WAL, so its floor pushes strictly
        supersede the dead predecessor's (epoch fencing)."""
        with self._mu:
            self.epoch += 1
            e = self.epoch
        self._append(REC_EPOCH, {"e": e})
        return e

    # ---- compaction ----

    def _compact_loop(self) -> None:
        while True:
            with self._mu:
                while not self._closed and not self._compact_due():
                    self._compact_cv.wait(0.5)
                if self._closed:
                    return
            try:
                self.compact()
            except Exception:
                import logging
                logging.getLogger(__name__).info(
                    "session WAL compaction failed", exc_info=True)
                time.sleep(0.5)

    def _compact_due(self) -> bool:
        if self.snapshot_source is None:
            return False
        if self.records < self.compact_min_records:
            return False
        try:
            return os.path.getsize(self.path) >= self.compact_bytes \
                or self.records >= self.compact_min_records
        except OSError:
            return False

    def compact(self) -> Optional[dict]:
        """Rewrite the log as one snapshot of the CURRENT table (epoch
        record + one ``snap`` per session) through an atomic rename.
        Returns the compaction stats row, or None without a
        ``snapshot_source``.

        The snapshot is taken UNDER the WAL lock: an append landing
        between snapshot and rename would otherwise be a durable token
        the rewrite silently drops — a write-ahead violation.  Lock
        order is therefore wal._mu -> table._mu -> session.mu, and no
        append path may hold a table/session lock when it reaches the
        WAL (the appenders in router.py release them first)."""
        src = self.snapshot_source
        if src is None:
            return None
        with self._mu:
            if self._closed:
                return None
            rows = src()
            before_records = self.records
            try:
                before_bytes = os.path.getsize(self.path)
            except OSError:
                before_bytes = 0
            tmp = self.path + ".compact"
            with open(tmp, "wb") as fp:
                w = RecordWriter(fp)
                w.write(json.dumps({"e": self.epoch},
                                   separators=(",", ":")).encode(),
                        REC_EPOCH)
                n = 1
                for r in rows:
                    row = {"s": r["sid"], "p": r["prompt"],
                           "b": r["budget"], "e": r["emitted"],
                           "st": r["state"], "ec": r["error_code"]}
                    m = r.get("model")
                    if m and m != DEFAULT_MODEL:
                        row["m"] = str(m)
                    w.write(json.dumps(
                        row, separators=(",", ":")).encode(), REC_SNAP)
                    n += 1
                w.flush()
                os.fsync(fp.fileno())
            self._fp.close()
            os.replace(tmp, self.path)
            self._fp = open(self.path, "ab")
            self._writer = RecordWriter(self._fp)
            # the snapshot supersedes any un-durable pending tail (its
            # tokens live in the table state just snapped); healing it
            # afterwards would replay stale open records over snaps
            self.healed_records += len(self._pending)
            self._pending.clear()
            self.records = n
            self.compactions += 1
            self.last_compaction = {
                "t": time.time(),
                "records_before": before_records, "records_after": n,
                "bytes_before": before_bytes,
                "bytes_after": os.path.getsize(self.path),
            }
            return dict(self.last_compaction)

    # ---- lifecycle / introspection ----

    def size_bytes(self) -> int:
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    def stats(self) -> dict:
        with self._mu:
            return {
                "path": self.path,
                "size_bytes": self.size_bytes(),
                "records": self.records,
                "epoch": self.epoch,
                "appends": self.appends,
                "append_failures": self.append_failures,
                "pending": len(self._pending),
                "healed_records": self.healed_records,
                "compactions": self.compactions,
                "last_compaction": (dict(self.last_compaction)
                                    if self.last_compaction else None),
                "replay": dict(self.replay),
            }

    def close(self) -> None:
        with self._mu:
            if self._closed:
                return
            self._closed = True
            self._compact_cv.notify_all()
            t = self._compact_thread
        if t is not None:
            t.join(5.0)
        with self._mu:
            try:
                self._writer.flush()
            except Exception:
                pass
            try:
                self._fp.close()
            except Exception:
                pass
