"""SLO burn-rate engine — the evaluator that closes the canary loop
(ISSUE 20; ROADMAP 4(b): "the canary split picks versions but nothing
promotes them").

Declarative OBJECTIVES per deployment key — TTFT p99, ITL p99, error
rate — are evaluated as MULTI-WINDOW BURN RATES over the fleet series
the :class:`~brpc_tpu.serving.telemetry.FleetCollector` maintains: an
objective is BURNING only when its burn (observed / target) exceeds
the threshold over BOTH a short window (fast detection) and a long
window (sustained, not a blip) — the standard SRE multi-window
burn-rate alert shape, chosen here for the same reason the gRPC
microbenchmark paper (PAPERS.md) measures in windows: fleet decisions
must ride measured windowed series, never point reads.

The engine's verdicts drive three outputs:

  * CANARY RAMP.  A canary (PR 18's smooth-WRR 95/5 split) is
    PROMOTED to 100/0 after N consecutive clean windows — the engine
    re-weights the canary warm and drains the baseline through the
    router's epoch-fenced ``deploy_model`` push, so a superseded
    router's promotion is refused like any stale floor push.  It is
    ROLLED BACK the moment the canary burns while the baseline does
    not (or burns ``rollback_margin`` times faster): baseline is
    re-weighted warm, canary drained.  Both endpoints are terminal —
    one decision per engine, with the full trail kept for /fleet.

  * DISRUPTION HOLD.  While the collector reports a tombstoned (or
    recently tombstoned/recovered) replica, every canary decision is
    HELD: chaos-induced burn (a killed replica's failed streams, the
    survivors' queueing) must neither promote nor roll back — the
    clean-window streak freezes and resumes when the fleet settles.

  * ADVISORY FLOOR.  :meth:`floor` is 1 while any objective burns —
    registered as a floor source on the router's overload ladder, it
    holds the gradient at level >= 1 (shed-at-router) without ever
    escalating further: SLO pressure is advice, the pressure gradient
    stays in charge of levels 2+.
"""
from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Optional

from brpc_tpu.butil.lockprof import InstrumentedLock

# verdicts
OK = "OK"
BURNING = "BURNING"
INSUFFICIENT = "INSUFFICIENT_DATA"
HOLD = "HOLD"

# terminal ramp states
RAMPING = "ramping"
PROMOTED = "promoted"
ROLLED_BACK = "rolled_back"

# metrics an Objective may target (all read from the router-sampled
# fleet series, replica="router")
METRIC_TTFT = "ttft_p99_ms"
METRIC_ITL = "itl_p99_ms"
METRIC_ERROR_RATE = "error_rate"


@dataclass(frozen=True)
class Objective:
    """One declarative objective: ``metric`` must stay at or under
    ``target`` (milliseconds for the latency metrics, a ratio for
    ``error_rate``).  Burn = observed / target."""
    metric: str
    target: float

    def __post_init__(self):
        if self.metric not in (METRIC_TTFT, METRIC_ITL,
                               METRIC_ERROR_RATE):
            raise ValueError(f"unknown SLO metric {self.metric!r}")
        if not self.target > 0:
            raise ValueError("SLO target must be positive")


def _burn(collector, model: str, metric: str, target: float,
          window_s: float, now: float) -> Optional[float]:
    """Burn rate of one (model, metric) over one trailing window, or
    None when the window holds too little data to judge."""
    if metric == METRIC_ERROR_RATE:
        fin = collector.window_values("router", model, "finished",
                                      window_s, now)
        fail = collector.window_values("router", model, "failed",
                                       window_s, now)
        if len(fin) < 2 or len(fail) < 2:
            return None
        d_fin = max(0.0, fin[-1] - fin[0])
        d_fail = max(0.0, fail[-1] - fail[0])
        total = d_fin + d_fail
        if total <= 0:
            return None   # no finishes this window: nothing to judge
        return (d_fail / total) / target
    vals = collector.window_values("router", model, metric, window_s, now)
    if len(vals) < 2:
        return None
    return (sum(vals) / len(vals)) / target


class SLOEngine:
    """Burn-rate evaluator + canary controller for ONE model_id's
    baseline/canary version pair (see module docstring).  Drive it
    from the router tick: :meth:`tick` evaluates, decides, and (at
    most once) pushes the promote/rollback through the router."""

    def __init__(self, model_id: str, baseline: str, canary: str,
                 objectives, *,
                 short_window_s: float = 2.0,
                 long_window_s: float = 6.0,
                 burn_threshold: float = 1.0,
                 rollback_margin: float = 1.5,
                 clean_windows: int = 3,
                 hold_window_s: Optional[float] = None,
                 trail_keep: int = 64,
                 act: bool = True):
        self.model_id = str(model_id)
        self.baseline = str(baseline)
        self.canary = str(canary)
        self.objectives = [o if isinstance(o, Objective)
                           else Objective(**o) for o in objectives]
        if not self.objectives:
            raise ValueError("SLOEngine needs at least one objective")
        self.short_window_s = float(short_window_s)
        self.long_window_s = float(long_window_s)
        self.burn_threshold = float(burn_threshold)
        self.rollback_margin = float(rollback_margin)
        self.clean_windows = max(1, int(clean_windows))
        self.hold_window_s = float(hold_window_s
                                   if hold_window_s is not None
                                   else long_window_s)
        # act=False is OBSERVE-ONLY: burns, verdicts, trail and the
        # advisory floor all run, but the engine never promotes or
        # rolls back (rpc_press --slo over a fleet with no real
        # baseline/canary pair to re-weight)
        self.act = bool(act)
        self._mu = InstrumentedLock("slo.engine")
        self.state = RAMPING
        self.clean_streak = 0
        self._last_window_t: Optional[float] = None
        self._last_verdict: Optional[str] = None
        self._burning_now = False
        self.evaluations = 0
        self.holds = 0
        self._trail: deque = deque(maxlen=max(8, int(trail_keep)))
        self._last_eval: dict = {}

    # ---- evaluation ---------------------------------------------------

    def _evaluate_key(self, collector, key: str,
                      now: float) -> tuple[str, dict]:
        """Verdict + per-objective burns for one deployment key:
        BURNING iff ANY objective burns over BOTH windows; OK iff every
        objective has data and none burns; INSUFFICIENT otherwise."""
        burns: dict[str, dict] = {}
        any_burning = False
        any_data = False
        all_data = True
        for o in self.objectives:
            bs = _burn(collector, key, o.metric, o.target,
                       self.short_window_s, now)
            bl = _burn(collector, key, o.metric, o.target,
                       self.long_window_s, now)
            burns[o.metric] = {
                "target": o.target,
                "short": round(bs, 3) if bs is not None else None,
                "long": round(bl, 3) if bl is not None else None,
            }
            if bs is None or bl is None:
                all_data = False
                continue
            any_data = True
            if bs > self.burn_threshold and bl > self.burn_threshold:
                any_burning = True
                burns[o.metric]["burning"] = True
        if any_burning:
            return BURNING, burns
        if not any_data or not all_data:
            return INSUFFICIENT, burns
        return OK, burns

    def _note(self, now: float, verdict: str, detail: str,
              action: Optional[str] = None) -> None:
        """Append to the decision trail on verdict CHANGES and actions
        (a 20Hz tick appending every evaluation would bury the story
        the /fleet page exists to tell)."""
        if action is None and verdict == self._last_verdict:
            return
        self._last_verdict = verdict
        self._trail.append({
            "t": round(time.time(), 3),
            "verdict": verdict,
            "state": self.state,
            "clean_windows": self.clean_streak,
            "detail": detail,
            **({"action": action} if action else {}),
        })

    # ---- the control loop ---------------------------------------------

    def tick(self, collector, router=None,
             now: Optional[float] = None) -> str:
        """One evaluation pass: returns the verdict and (at most once,
        ever) pushes a promote/rollback through ``router``.  Safe to
        call from the router's tick thread at any cadence — windows are
        measured in time, not ticks."""
        now = time.monotonic() if now is None else now
        with self._mu:
            self.evaluations += 1
            can_v, can_b = self._evaluate_key(collector, self.canary, now)
            base_v, base_b = self._evaluate_key(collector, self.baseline,
                                                now)
            # the advisory floor follows only deployments still taking
            # traffic: after a terminal decision the LOSER is drained,
            # and its frozen percentile reservoir (ModelMetrics is
            # cumulative) would otherwise read BURNING forever and pin
            # the fleet at shed-at-router
            if self.state == ROLLED_BACK:
                self._burning_now = base_v == BURNING
            elif self.state == PROMOTED:
                self._burning_now = can_v == BURNING
            else:
                self._burning_now = BURNING in (can_v, base_v)
            self._last_eval = {
                "t": round(time.time(), 3),
                "canary": {"verdict": can_v, "burns": can_b},
                "baseline": {"verdict": base_v, "burns": base_b},
            }
            if not self.act:
                self._note(now, can_v, "observe-only evaluation")
                return can_v
            if self.state != RAMPING:
                return self.state
            if collector.disruption_within(self.hold_window_s, now):
                self.holds += 1
                self._note(now, HOLD,
                           f"disruption window active "
                           f"(tombstoned={collector.tombstoned()}): "
                           f"canary ramp frozen")
                return HOLD
            if can_v == BURNING:
                worse = self._canary_burns_faster(can_b, base_b)
                if base_v != BURNING or worse:
                    self.state = ROLLED_BACK
                    self.clean_streak = 0
                    self._note(now, BURNING,
                               f"canary {self.canary} burning "
                               f"(baseline {base_v}): rolling back "
                               f"to {self.baseline} 100/0",
                               action="rollback")
                    if router is not None:
                        self._push(router, keep=self.baseline,
                                   drain=self.canary)
                    return BURNING
                # the whole fleet burns: not the canary's fault — hold
                # the ramp, let the advisory floor do its job
                self.clean_streak = 0
                self._note(now, BURNING,
                           "baseline burning too: fleet-wide pressure, "
                           "no canary verdict")
                return BURNING
            if can_v == OK:
                if (self._last_window_t is None
                        or now - self._last_window_t
                        >= self.short_window_s):
                    self._last_window_t = now
                    self.clean_streak += 1
                    self._note(now, OK,
                               f"clean window {self.clean_streak}/"
                               f"{self.clean_windows} for {self.canary}",
                               action="clean_window")
                if self.clean_streak >= self.clean_windows:
                    self.state = PROMOTED
                    self._note(now, OK,
                               f"{self.clean_streak} clean windows: "
                               f"promoting {self.canary} to 100/0",
                               action="promote")
                    if router is not None:
                        self._push(router, keep=self.canary,
                                   drain=self.baseline)
                return OK
            self._note(now, INSUFFICIENT,
                       f"not enough windowed data for {self.canary}")
            return INSUFFICIENT

    def _canary_burns_faster(self, can_b: dict, base_b: dict) -> bool:
        for metric, cb in can_b.items():
            bl = cb.get("long")
            if bl is None:
                continue
            ob = (base_b.get(metric) or {}).get("long")
            if ob is None or bl > ob * self.rollback_margin:
                return True
        return False

    @staticmethod
    def _push(router, *, keep: str, drain: str) -> None:
        """The ramp mutation: winner re-deployed warm at weight 1,
        loser drained — the smooth-WRR split then routes 100/0 because
        ``version_weights`` excludes DRAINING keys.  Rides the
        epoch-fenced ``deploy_model`` push; partial failure is re-tried
        by the next deploy, never by re-deciding."""
        router.deploy_model(keep, op="deploy", weight=1, state="warm")
        router.deploy_model(drain, op="drain")

    # ---- outputs ------------------------------------------------------

    def floor(self) -> int:
        """Advisory overload-ladder floor: 1 while any objective burns
        (shed at the router), 0 otherwise.  Never higher — SLO pressure
        advises, the pressure gradient escalates."""
        with self._mu:
            return 1 if self._burning_now else 0

    def trail(self) -> list[dict]:
        with self._mu:
            return list(self._trail)

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "model_id": self.model_id,
                "baseline": self.baseline,
                "canary": self.canary,
                "state": self.state,
                "objectives": [{"metric": o.metric, "target": o.target}
                               for o in self.objectives],
                "windows_s": {"short": self.short_window_s,
                              "long": self.long_window_s,
                              "hold": self.hold_window_s},
                "burn_threshold": self.burn_threshold,
                "clean_windows": {"streak": self.clean_streak,
                                  "required": self.clean_windows},
                "evaluations": self.evaluations,
                "holds": self.holds,
                "floor": 1 if self._burning_now else 0,
                "last_eval": dict(self._last_eval),
                "trail": list(self._trail),
            }


__all__ = [
    "OK", "BURNING", "INSUFFICIENT", "HOLD",
    "RAMPING", "PROMOTED", "ROLLED_BACK",
    "METRIC_TTFT", "METRIC_ITL", "METRIC_ERROR_RATE",
    "Objective", "SLOEngine",
]
