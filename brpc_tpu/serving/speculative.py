"""Draft proposers for speculative decoding (ISSUE 11).

The DecodeEngine's speculative mode is propose -> verify -> commit:
something cheap PROPOSES the next k tokens (a linear chain, or a
shallow tree of alternative continuations), the TARGET model verifies
the whole proposal in one paged-attention call, and the longest
greedy-matching prefix commits — output is bit-identical to plain
greedy decode, only the cost per emitted token changes.  This module
is the "something cheap":

  :class:`DraftProposer`       the contract — ``propose(tokens, k)``
                               returns a list of BRANCHES (each a
                               token chain continuing the context;
                               branch order is priority, total tokens
                               across branches <= k).  One branch is a
                               linear chain; several are a draft tree.
  :class:`NGramProposer`       prompt-lookup decoding: the longest
                               recent n-gram suffix match in the
                               context predicts what follows.  Pure
                               host work — the draft cost the ISSUE's
                               "draft runner ≪ target runner" bench
                               operating point assumes — and very
                               accurate on self-repeating output
                               (which greedy decode produces in
                               abundance).
  :class:`DraftModelProposer`  a small draft MODEL: greedy chains via
                               the cache-less dense forward of
                               ``models/runner.py``.  ``width > 1``
                               branches from the top-w first tokens —
                               the draft-tree shape.

``as_proposer`` adapts what the engine is handed: a proposer passes
through, a :class:`~brpc_tpu.models.runner.TransformerRunner` (or
anything carrying ``params``/``cfg``) wraps as a DraftModelProposer.
"""
from __future__ import annotations

from typing import Optional, Sequence

__all__ = ["DraftProposer", "NGramProposer", "DraftModelProposer",
           "as_proposer"]


class DraftProposer:
    """The proposer contract (see module docstring)."""

    name = "draft"

    def propose(self, tokens: Sequence[int],
                k: int) -> list[list[int]]:
        """Up to ``k`` draft tokens continuing ``tokens``, as a list
        of branches (possibly empty — propose nothing when there is no
        basis for a guess; the engine then runs a plain step)."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class NGramProposer(DraftProposer):
    """Prompt-lookup drafts: find the most recent earlier occurrence
    of the context's longest suffix n-gram (n down to 1) and propose
    the tokens that followed it.  ``width > 1`` proposes one branch
    per DISTINCT continuation over the most recent matches — a shallow
    draft tree for contexts whose history diverges."""

    def __init__(self, n: int = 3, width: int = 1,
                 window: int = 256, name: str = "ngram"):
        if n < 1 or width < 1 or window < 2:
            raise ValueError("n, width and window must be sane")
        self.n = int(n)
        self.width = int(width)
        # bounded LOOKBACK: propose from the last `window` tokens only
        # — the scan runs on the engine's step-loop thread every
        # iteration, and an unbounded match over a 32k context would
        # make proposer cost grow with sequence length.  Self-repeating
        # output (the regime where drafts accept at all) cycles well
        # inside a few hundred tokens.
        self.window = int(window)
        self.name = name

    def _matches(self, toks: list, g: int) -> list[int]:
        """End positions (exclusive) of earlier occurrences of the
        length-``g`` suffix, most recent first."""
        suf = toks[-g:]
        out = []
        for j in range(len(toks) - g - 1, -1, -1):
            if toks[j:j + g] == suf:
                out.append(j + g)
        return out

    def propose(self, tokens: Sequence[int],
                k: int) -> list[list[int]]:
        toks = [int(t) for t in tokens[-self.window:]]
        if k < 1 or len(toks) < 2:
            return []
        for g in range(min(self.n, len(toks) - 1), 0, -1):
            ends = self._matches(toks, g)
            if not ends:
                continue
            per = max(1, k // self.width)
            branches: list[list[int]] = []
            seen_first = set()
            budget = k
            for e in ends:
                if len(branches) >= self.width or budget <= 0:
                    break
                want = min(per, budget)
                cont = toks[e:e + want]
                if not cont or cont[0] in seen_first:
                    continue
                if len(cont) < want:
                    # the most recent occurrence sits too close to the
                    # end to supply a full chain (the common case on a
                    # short-period cycle); prefer an EARLIER occurrence
                    # of the same continuation with more road ahead
                    for e2 in ends:
                        c2 = toks[e2:e2 + want]
                        if c2 and c2[0] == cont[0] \
                                and len(c2) > len(cont):
                            cont = c2
                            if len(cont) >= want:
                                break
                seen_first.add(cont[0])
                branches.append(cont)
                budget -= len(cont)
            if branches:
                return branches
        return []


class DraftModelProposer(DraftProposer):
    """A small draft model as the proposer: greedy continuation chains
    through the cache-less dense forward (``models/runner.py``).  Cost
    scales with the draft model's size — the point is a draft much
    smaller than the target.  ``width > 1`` branches on the top-w
    first tokens, each extended greedily (the draft-tree shape)."""

    def __init__(self, params: dict, cfg, *, width: int = 1,
                 name: str = "draft-model"):
        if width < 1:
            raise ValueError("width must be >= 1")
        self.params = params
        self.cfg = cfg
        self.width = int(width)
        self.name = name

    def _next_logits(self, toks: list):
        import jax.numpy as jnp

        from brpc_tpu.models.runner import dense_forward
        t = jnp.asarray([toks], jnp.int32)
        p = jnp.arange(len(toks), dtype=jnp.int32)[None]
        return dense_forward(self.params, self.cfg, t, p)[0, -1]

    def propose(self, tokens: Sequence[int],
                k: int) -> list[list[int]]:
        import jax.numpy as jnp
        toks = [int(t) for t in tokens]
        if k < 1 or not toks:
            return []
        logits = self._next_logits(toks)
        width = min(self.width, k)
        if width == 1:
            firsts = [int(jnp.argmax(logits))]
        else:
            firsts = [int(i) for i in
                      jnp.argsort(logits)[::-1][:width]]
        per = max(1, k // len(firsts))
        branches = []
        budget = k
        for t0 in firsts:
            if budget <= 0:
                break
            b = [t0]
            cur = toks + [t0]
            while len(b) < min(per, budget):
                nxt = int(jnp.argmax(self._next_logits(cur)))
                b.append(nxt)
                cur.append(nxt)
            branches.append(b)
            budget -= len(b)
        return branches


def as_proposer(draft) -> Optional[DraftProposer]:
    """Adapt the engine's ``draft_runner=`` argument: None passes
    through, a proposer passes through, a model runner carrying
    ``params``/``cfg`` (TransformerRunner) wraps as a
    :class:`DraftModelProposer`."""
    if draft is None:
        return None
    if hasattr(draft, "propose"):
        return draft
    params = getattr(draft, "params", None)
    cfg = getattr(draft, "cfg", None)
    if params is not None and cfg is not None:
        return DraftModelProposer(params, cfg,
                                  name=f"draft:{getattr(draft, 'name', 'model')}")
    raise ValueError(
        f"draft_runner must be a DraftProposer or a model runner with "
        f"params/cfg, got {type(draft).__name__}")
