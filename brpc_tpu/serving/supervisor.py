"""EngineSupervisor — crash recovery and overload degradation for the
decode engine.

bRPC's resilience machinery (health-check revival, circuit breaking,
backup requests) lives at the CHANNEL boundary; the serving stack has
its own failure domain with nothing watching it: if the DecodeEngine's
step loop crashes or wedges mid-decode, every in-flight generation is
lost even though their KV pages sit safely in a KVCacheStore that
outlives the engine.  The supervisor closes that gap — "the framework
heals itself" applied to the data path:

WATCHDOG.  The engine publishes a step-progress heartbeat every loop
iteration.  The supervisor flags a failure when (a) the engine's crash
handler fires (a step exception, e.g. the ``serving.step`` fault
site), (b) the loop thread has died, or (c) work is pending but the
heartbeat has not advanced within ``heartbeat_deadline_s`` — a WEDGED
loop (simulated deterministically by the ``serving.heartbeat`` fault
site, which suppresses beats while the loop runs).

RECOVERY.  On failure the supervisor takes over the engine's slots and
waiters WITHOUT completing them, re-attaches each in-flight sequence's
committed full pages to the radix tree under a recovery pin
(``KVCacheStore.detach`` — pressure eviction cannot free the prefix
before re-admission), tears the engine down, rebuilds a fresh
``DecodeEngine`` against the SAME store, and re-admits every request
resuming from its last emitted token: the resume prompt is
``original_prompt + emitted_tokens``, so admission prefix-hits the
committed pages and only the uncommitted tail re-decodes.
Exactly-once emission holds across the seam by construction: the
per-request emitted-token CURSOR advances only when a token reaches
the consumer, tokens buffered at crash time flush through the old
emitter before the restart marker, and the resumed decode starts
after the cursor — no duplicated and no dropped tokens.

DEGRADATION LADDER.  Each watchdog tick reads the batcher's queue
delay, the engine's queue depth, and the page pool's occupancy, and
maps them onto brownout levels:

  level 1  shed the lowest-priority lane (deadline-less requests)
           at batcher admission;
  level 2  + clamp ``max_new_tokens`` for new engine submissions;
  level 3  + aggressively evict cached (tree-only) KV pages each tick.

Levels step UP immediately and step DOWN one at a time only after
``hysteresis_ticks`` consecutive calm ticks, so an oscillating load
cannot flap the ladder.

FLAPPING REPLICAS.  Every crash is reported to the global circuit
breaker; once ``quarantine_after`` crashes accumulate inside
``restart_window_s`` the supervisor's advertised ``endpoint`` is
marked broken with the breaker's exponential isolation hold — load
balancers (including ``prefix_affinity``) stop selecting it, and the
consistent-hash ring remaps ONLY the quarantined replica's share of
prefixes.  After ``max_restarts`` crashes in the window the
supervisor stops rebuilding and fails pending requests definitively
(a permanently broken engine must not burn the machine rebuilding
forever).

``submit`` has the DecodeEngine signature, so a supervisor drops into
``register_serving(engine=...)`` unchanged and the ``/serving``
console page shows its state, restart count, and last recovery stats.
"""
from __future__ import annotations

import itertools
import re
import threading
from brpc_tpu.butil.lockprof import InstrumentedLock
import time
from typing import Callable, Optional, Sequence

from brpc_tpu import errors, rpcz
from brpc_tpu.bvar import Adder, PassiveStatus
from brpc_tpu.serving.ladder import OverloadLadder

_sup_req_ids = itertools.count(1)

# default ladder thresholds per level (1..3): queue-delay p99 (us),
# page-pool occupancy ratio, engine queue depth per slot
DEFAULT_LADDER = (
    {"queue_delay_us": 50_000.0, "pool_ratio": 0.75, "queue_depth": 2.0},
    {"queue_delay_us": 100_000.0, "pool_ratio": 0.88, "queue_depth": 4.0},
    {"queue_delay_us": 200_000.0, "pool_ratio": 0.96, "queue_depth": 8.0},
)


class _SupReq:
    """One supervised generation: the original request plus the
    emitted-token cursor that makes recovery exactly-once."""

    __slots__ = ("sid", "prompt", "max_new_tokens", "user_emit",
                 "user_done", "emitted", "restarts", "finished", "pin",
                 "resumed", "trace", "attempt_span", "last_span_id",
                 "t_start", "mu", "delivery_mu", "speculative")

    def __init__(self, prompt, max_new_tokens, emit, on_done,
                 speculative: bool = True):
        self.sid = next(_sup_req_ids)
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.user_emit = emit
        self.user_done = on_done
        # sticky across restarts: a re-admission keeps the request's
        # speculative opt-in/out (ISSUE 11)
        self.speculative = bool(speculative)
        self.emitted: list[int] = []   # the exactly-once cursor
        self.restarts = 0
        self.finished = False
        self.pin = None                # RecoveryPin while re-admitting
        # rpcz generation trace (ISSUE 5): the trace context captured at
        # submission is STABLE across engine restarts, so the pre-crash
        # and post-crash attempt spans share ONE trace_id; each attempt
        # span links its predecessor via recovered_from
        self.trace = rpcz.current_trace_ctx()
        self.attempt_span = rpcz.NULL_SPAN   # current attempt's span
        self.last_span_id = 0                # previous attempt's span id
        self.t_start = time.monotonic()
        # True between a post-crash re-admission and its first token:
        # distinguishes the NEW engine's first token (recovery proven:
        # release the pin, stamp time-to-recover) from pre-crash tokens
        # still flushing out of the old emitter's buffer
        self.resumed = False
        self.mu = threading.Lock()
        # serializes token delivery against the terminal: user_done
        # must WAIT for an in-flight user_emit and no token may follow
        # it.  Separate from `mu` (never held during user callbacks)
        # and always acquired FIRST when both are needed.
        self.delivery_mu = threading.Lock()


class EngineSupervisor:
    """Watchdog + crash recovery + overload ladder for a DecodeEngine
    (see module docstring)."""

    def __init__(self, engine_factory: Callable, *,
                 store=None,
                 batcher=None,
                 heartbeat_deadline_s: float = 5.0,
                 check_interval_s: float = 0.1,
                 max_restarts: int = 8,
                 restart_window_s: float = 60.0,
                 quarantine_after: int = 3,
                 endpoint=None,
                 ladder: Sequence[dict] = DEFAULT_LADDER,
                 clamp_new_tokens: int = 32,
                 ladder_evict_pages: Optional[int] = None,
                 hysteresis_ticks: int = 5,
                 name: str = "supervisor"):
        self.engine_factory = engine_factory
        # the store is CALLER-owned and shared across engine
        # incarnations — that is the whole point: radix-tree
        # persistence across restarts makes recovery prefill-skip free
        self.store = store
        self.batcher = batcher
        self.heartbeat_deadline_s = float(heartbeat_deadline_s)
        self.check_interval_s = float(check_interval_s)
        self.max_restarts = int(max_restarts)
        self.restart_window_s = float(restart_window_s)
        self.quarantine_after = int(quarantine_after)
        self.endpoint = endpoint
        self.ladder = tuple(ladder)
        self.clamp_new_tokens = int(clamp_new_tokens)
        self.ladder_evict_pages = ladder_evict_pages
        self.hysteresis_ticks = int(hysteresis_ticks)
        self.name = name

        # escalation/hysteresis policy shared with the cluster router
        # (serving/ladder.py, ISSUE 8): the supervisor keeps its three
        # in-process levels, the state machine is the common one
        self._ladder = OverloadLadder(self.ladder,
                                      hysteresis_ticks=self.hysteresis_ticks)
        self.state = "healthy"          # healthy|degraded|restarting|failed
        self.last_recovery: Optional[dict] = None
        self._restart_times: list[float] = []
        self._await_first_token_t: Optional[float] = None

        self._mu = InstrumentedLock("supervisor.state")
        self._live: dict[int, _SupReq] = {}      # sid -> request
        self._by_rid: dict[int, _SupReq] = {}    # engine req_id -> request
        self._closing = False
        self._failed = False

        safe = re.sub(r"\W", "_", name)
        from brpc_tpu.bvar.variable import exposed_variables
        pre = set(exposed_variables(f"serving_{safe}*"))
        self.restarts_total = Adder(f"serving_{safe}_restarts")
        self.readmitted = Adder(f"serving_{safe}_readmitted")
        self.resumed_tokens = Adder(f"serving_{safe}_resumed_tokens")
        self.ladder_evictions = Adder(f"serving_{safe}_ladder_evictions")
        PassiveStatus(lambda: self.level).expose(
            f"serving_{safe}_brownout_level")
        self._bvar_names = [n for n in exposed_variables(f"serving_{safe}*")
                            if n not in pre]

        # engine handoff: _engine is None while a rebuild is in flight;
        # re-admissions wait on the condition instead of failing
        self._ecv = threading.Condition(
            InstrumentedLock("supervisor.engine"))
        self._engine = None
        self._wake = threading.Event()
        self._running = True
        self._engine = self._build_engine()
        self._thread = threading.Thread(
            target=self._watchdog, daemon=True,
            name=f"serving-supervisor-{safe}")
        self._thread.start()
        from brpc_tpu import serving as _serving
        _serving._register_supervisor(self)

    # ---- engine lifecycle ----

    def _build_engine(self):
        eng = self.engine_factory()
        eng.set_crash_handler(self._on_engine_crash)
        eng.degraded_clamp = self.clamp_new_tokens if self.level >= 2 \
            else None
        return eng

    def _on_engine_crash(self, engine, exc) -> None:
        # runs on the dying engine thread: only signal the watchdog
        self._wake.set()

    def _engine_now(self, timeout_s: float = 30.0):
        """The current engine, waiting out an in-flight rebuild."""
        deadline = time.monotonic() + timeout_s
        with self._ecv:
            while self._engine is None and not self._failed \
                    and not self._closing:
                rem = deadline - time.monotonic()
                if rem <= 0:
                    return None
                self._ecv.wait(rem)
            return self._engine

    @property
    def engine(self):
        return self._engine

    # ---- submission (DecodeEngine-compatible) ----

    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               emit: Callable[[int], None],
               on_done: Optional[Callable] = None, *,
               speculative: bool = True) -> int:
        """Supervised generation: same contract as DecodeEngine.submit
        — tokens via ``emit`` (exactly once each, across any number of
        engine restarts), one terminal ``on_done(err)`` — plus
        automatic re-admission if the engine dies mid-decode."""
        # the ladder's clamp is decided ONCE, here: the budget a
        # request is admitted with is the budget it keeps through any
        # number of restarts (engine-level clamping is bypassed below,
        # or a level-2 brownout at restart time would silently truncate
        # an in-flight generation — and a brownout at ADMISSION time
        # would silently un-clamp on the first restart)
        if self.level >= 2:
            max_new_tokens = min(int(max_new_tokens),
                                 self.clamp_new_tokens)
        sreq = _SupReq(prompt, max_new_tokens, emit, on_done,
                       speculative=speculative)
        with self._mu:
            if self._closing or self._failed:
                closing = True
            else:
                closing = False
                self._live[sreq.sid] = sreq
        if closing:
            self._finish(sreq, errors.RpcError(
                errors.ELOGOFF, "supervisor closed"))
            return sreq.sid
        self._submit_to_engine(sreq)
        return sreq.sid

    def _submit_to_engine(self, sreq: _SupReq) -> bool:
        with sreq.mu:
            emitted = list(sreq.emitted)
        remaining = sreq.max_new_tokens - len(emitted)
        if remaining <= 0:
            # the full budget was generated before the crash: nothing
            # to re-decode, the request simply completes
            self._finish(sreq, None)
            return True
        eng = self._engine_now()
        if eng is None:
            self._finish(sreq, errors.RpcError(
                errors.EINTERNAL,
                "supervisor gave up rebuilding the engine"))
            return False
        # resume prompt = original + emitted: admission prefix-hits the
        # pages detach() committed, so only the uncommitted tail
        # re-decodes — and decode restarts from the exact (token,
        # position) the crashed loop would have used next, making the
        # resumed stream bit-exact for any position/token step function
        resume_prompt = sreq.prompt + emitted
        ctx = self._open_attempt_span(sreq, resume_prompt, remaining,
                                      len(emitted))
        with sreq.mu:
            sreq.resumed = sreq.restarts > 0
        rid = eng.submit(resume_prompt, remaining,
                         lambda tok, s=sreq: self._emit(s, tok),
                         lambda err, s=sreq: self._req_done(s, err),
                         clamp=False, trace_ctx=ctx,
                         speculative=sreq.speculative)
        with self._mu:
            self._by_rid[rid] = sreq
        return True

    # ---- generation tracing (ISSUE 5) ----

    def _open_attempt_span(self, sreq: _SupReq, resume_prompt,
                           remaining: int, cursor: int) -> tuple:
        """One rpcz span per engine attempt of a supervised generation.
        Every attempt joins the SAME trace (the context captured at
        submit, made stable on the first attempt); a post-crash attempt
        links its predecessor via ``recovered_from`` and annotates the
        resume cursor and the re-decoded-token count, so a single
        ``/rpcz?trace_id=`` timeline shows the full pre-crash/post-crash
        story.  Returns the trace_ctx to hand the engine so decode and
        prefill spans nest under the attempt."""
        with sreq.mu:
            tid, psid, smp = sreq.trace
            restarts = sreq.restarts
            last_sid = sreq.last_span_id
        span = rpcz.new_span("generation", "Serving", self.name,
                             trace_id=tid, parent_span_id=psid,
                             sampled=smp if tid else None)
        if span is rpcz.NULL_SPAN:
            return (tid, psid, smp)
        if restarts:
            span.recovered_from = last_sid
            span.annotate(
                f"recovered_from=span {last_sid}: restart {restarts}, "
                f"resume_cursor={cursor} tokens already emitted, "
                f"{remaining} remaining")
            if self.store is not None:
                # how much of the resume prompt the committed pages
                # cover (advisory probe): the uncovered tail is what
                # this recovery actually re-decodes
                try:
                    hit = int(self.store.probe(resume_prompt))
                except Exception:
                    hit = 0
                span.annotate(
                    f"re_decoded_tokens={len(resume_prompt) - hit} "
                    f"(committed prefix hit={hit} of "
                    f"{len(resume_prompt)})")
        with sreq.mu:
            sreq.attempt_span = span
            sreq.last_span_id = span.span_id
            if not tid:
                # first attempt rooted the trace: later attempts (and
                # this generation only) must reuse it, or each restart
                # would start an unlinked fresh trace
                sreq.trace = (span.trace_id, psid, span.sampled)
        return (span.trace_id, span.span_id, span.sampled)

    def _close_attempt_span(self, sreq: _SupReq, err,
                            note: Optional[str] = None) -> None:
        """Submit the current attempt span exactly once (the swap to
        NULL_SPAN under the lock is the once-guard)."""
        with sreq.mu:
            span, sreq.attempt_span = sreq.attempt_span, rpcz.NULL_SPAN
        if span is rpcz.NULL_SPAN:
            return
        if err is not None:
            span.error_code = err.code
        if note:
            span.annotate(note)
        rpcz.submit(span)

    # ---- per-request plumbing ----

    def _emit(self, sreq: _SupReq, tok: int) -> None:
        with sreq.delivery_mu:
            with sreq.mu:
                if sreq.finished:
                    # terminal already delivered (close / give-up raced
                    # a flushing old emitter): a token after on_done
                    # would break every consumer's teardown contract
                    return
                sreq.emitted.append(tok)  # cursor first: delivered-once
                first_resumed = sreq.resumed
                sreq.resumed = False
                aspan = sreq.attempt_span if first_resumed else None
                pin = None
                if first_resumed:
                    # this token came from the REBUILT engine, so
                    # admission has re-taken its own refs — the
                    # recovery pin has done its job.  A pre-crash token
                    # flushing from the old emitter proves nothing and
                    # must keep the pin held.
                    pin, sreq.pin = sreq.pin, None
            if pin is not None:
                pin.release()
            if aspan is not None and aspan is not rpcz.NULL_SPAN:
                aspan.annotate("first post-recovery token delivered "
                               "(recovery pin released)")
            if first_resumed:
                t0 = self._await_first_token_t
                if t0 is not None:
                    self._await_first_token_t = None
                    if self.last_recovery is not None:
                        self.last_recovery["detect_to_first_token_ms"] \
                            = round((time.monotonic() - t0) * 1e3, 2)
            # delivered INSIDE delivery_mu (but outside the state
            # lock): a concurrent _finish blocks on delivery_mu until
            # this write lands, so the terminal can never overtake it
            sreq.user_emit(tok)

    def _req_done(self, sreq: _SupReq, err) -> None:
        if err is not None and err.code == errors.ELOGOFF \
                and not self._closing and not self._failed:
            # the ENGINE died under this request, the request itself is
            # fine: re-admit it, resuming after the emitted cursor.
            # Bounded by the supervisor's own restart budget — a
            # permanently-failing engine flips _failed and the next
            # terminal passes through as a definite error.
            with sreq.mu:
                sreq.restarts += 1
                give_up = sreq.restarts > self.max_restarts
            if not give_up:
                self._close_attempt_span(
                    sreq, err, "engine died mid-decode; re-admitting "
                    "after the emitted cursor")
                self.readmitted.add(1)
                with sreq.mu:
                    self.resumed_tokens.add(len(sreq.emitted))
                self._submit_to_engine(sreq)
                return
        self._finish(sreq, err)

    def _finish(self, sreq: _SupReq, err) -> None:
        with sreq.delivery_mu:
            # taking delivery_mu FIRST (same order as _emit) waits out
            # an in-flight token delivery and fences later ones: once
            # finished flips under the state lock below, _emit's
            # check sees it before any further user_emit
            with sreq.mu:
                if sreq.finished:
                    return
                sreq.finished = True
                pin, sreq.pin = sreq.pin, None
            if pin is not None:
                pin.release()
            with self._mu:
                self._live.pop(sreq.sid, None)
            self._close_attempt_span(sreq, err)
            with sreq.mu:
                emitted = len(sreq.emitted)
                restarts = sreq.restarts
            try:
                from brpc_tpu import serving as _serving
                _serving.record_generation({
                    "supervisor": self.name,
                    "sid": sreq.sid,
                    "trace_id": sreq.trace[0],
                    "prompt_len": len(sreq.prompt),
                    "emitted": emitted,
                    "restarts": restarts,
                    "duration_us": int(
                        (time.monotonic() - sreq.t_start) * 1e6),
                    "error_code": err.code if err is not None else 0,
                })
            except Exception:
                pass  # the console ring must never break a terminal
            if sreq.user_done is not None:
                try:
                    sreq.user_done(err)
                except Exception:
                    import logging
                    logging.getLogger(__name__).exception(
                        "supervised on_done callback raised")

    # ---- the watchdog ----

    def _watchdog(self) -> None:
        while True:
            self._wake.wait(self.check_interval_s)
            self._wake.clear()
            if not self._running:
                return
            eng = self._engine
            reason = None
            if eng is not None:
                if eng.crashed is not None:
                    reason = (f"step crash: "
                              f"{type(eng.crashed).__name__}: "
                              f"{eng.crashed}")
                elif not eng._thread.is_alive():
                    reason = "engine thread died"
                else:
                    _, beat_t = eng.heartbeat()
                    age = time.monotonic() - beat_t
                    if age > self.heartbeat_deadline_s and eng.has_work():
                        reason = (f"wedged step loop: no progress for "
                                  f"{age:.2f}s with work pending")
            try:
                if reason is not None:
                    self._recover(reason)
                if not self._running:
                    return
                self._update_degradation()
            except Exception:
                # the watchdog IS the robustness feature: it must
                # survive its own bugs or the supervisor silently
                # stops supervising
                import logging
                logging.getLogger(__name__).exception(
                    "supervisor watchdog tick failed")

    # ---- crash recovery ----

    def _recover(self, reason: str) -> None:
        t_detect = time.monotonic()
        self.state = "restarting"
        self.restarts_total.add(1)
        self._restart_times.append(t_detect)
        self._restart_times = [t for t in self._restart_times
                               if t > t_detect - self.restart_window_s]
        old = self._engine
        with self._ecv:
            self._engine = None         # re-admissions park on _engine_now
        stolen, waiters = old.takeover()
        restart_err = errors.RpcError(
            errors.ELOGOFF, "engine restarting (supervisor takeover)")
        pinned = 0
        for slot in stolen:
            with self._mu:
                sreq = self._by_rid.pop(slot.req.req_id, None)
            if slot.seq is not None and self.store is not None:
                try:
                    pin = self.store.detach(slot.seq)
                except Exception:
                    pin = None
                if pin is not None and len(pin):
                    pinned += 1
                    if sreq is not None:
                        with sreq.mu:
                            old_pin, sreq.pin = sreq.pin, pin
                            # new recovery epoch: tokens this engine
                            # generation buffered-but-never-delivered
                            # are about to flush, and they must not be
                            # mistaken for the NEXT generation's first
                            # token (premature pin release)
                            sreq.resumed = False
                        if old_pin is not None:
                            old_pin.release()
                    else:
                        pin.release()   # nobody to re-admit (direct user)
            elif slot.block is not None:
                try:
                    slot.block.free()
                except Exception:
                    pass
            # the pre-crash decode span ends HERE (the slot will never
            # retire through the dead engine): it stays part of the
            # generation's trace, so the timeline shows decode-up-to-
            # crash followed by the recovered_from-linked re-attempt
            if slot.span is not rpcz.NULL_SPAN:
                slot.span.error_code = errors.ELOGOFF
                slot.span.annotate(
                    f"engine takeover: {reason}; {slot.generated} "
                    f"tokens decoded pre-crash")
                rpcz.submit(slot.span)
            # the old emitter flushes every token already decoded into
            # the buffer (the cursor counts them — they are NOT
            # re-decoded), then delivers the restart marker, whose
            # _req_done re-admits the request.  Emission stays a single
            # ordered stream per request across the seam.  Emitters run
            # on their own threads; their resubmissions park in
            # _engine_now until the rebuild below lands.
            slot.req.buf.push_terminal(restart_err)
        with self._mu:
            # any rid not stolen/queued (e.g. mid-admission) belongs to
            # the dead engine too; its ELOGOFF terminal re-admits via
            # the wrapper, the stale mapping must not linger
            self._by_rid.clear()
        old.close(timeout_s=1.0)
        self._report_crash()
        gave_up = len(self._restart_times) > self.max_restarts
        if not gave_up:
            try:
                new = self._build_engine()
            except Exception as e:
                # a factory that cannot produce an engine strands every
                # parked re-admission in _engine_now: fail DEFINITIVELY
                # instead of leaving state 'restarting' forever
                gave_up = True
                reason = (f"{reason}; rebuild failed: "
                          f"{type(e).__name__}: {e}")
        if gave_up:
            self._fail_permanently(reason)
        else:
            # stamp the recovery record BEFORE publishing the engine:
            # parked re-admissions wake on the publish, and a fast
            # first token must find _await_first_token_t/last_recovery
            # already in place or the time-to-recover stat is lost
            self.last_recovery = {
                "reason": reason,
                "stolen_slots": len(stolen),
                "queued_waiters": len(waiters),
                "pinned_seqs": pinned,
                "detect_to_rebuild_ms": round(
                    (time.monotonic() - t_detect) * 1e3, 2),
            }
            self._await_first_token_t = t_detect
            with self._ecv:
                self._engine = new
                self._ecv.notify_all()
            self.state = "degraded" if self.level else "healthy"
        # finish the never-admitted waiters LAST: finish() runs the
        # resubmission wrapper synchronously on THIS thread, which must
        # not park in _engine_now before the rebuild above publishes
        # the replacement engine (deadlock: the parked thread would be
        # the one owing the rebuild)
        for req in waiters:
            req.finish(restart_err)

    def _fail_permanently(self, reason: str) -> None:
        """Too many crashes inside the window: stop rebuilding.  Every
        pending request gets a definite error — a permanently broken
        engine must fail fast, not rebuild forever."""
        with self._ecv:
            self._failed = True
            self._ecv.notify_all()
        self.state = "failed"
        err = errors.RpcError(
            errors.EINTERNAL,
            f"engine supervisor gave up after "
            f"{len(self._restart_times)} restarts in "
            f"{self.restart_window_s:.0f}s: {reason}")
        with self._mu:
            live = list(self._live.values())
        for sreq in live:
            self._finish(sreq, err)

    def _report_crash(self) -> None:
        """Wire repeated crashes into the channel-level recovery stack:
        the breaker's isolation counter grows per crash (so holds
        double), and past `quarantine_after` crashes in the window the
        replica's endpoint is marked broken — prefix_affinity and every
        other balancer stop selecting it, remapping only ITS share of
        the consistent-hash ring until the health probe revives it."""
        if self.endpoint is None:
            return
        try:
            from brpc_tpu.policy.circuit_breaker import global_breaker
            breaker = global_breaker()
            breaker.on_socket_failed(self.endpoint)   # isolation count +1
            if len(self._restart_times) >= self.quarantine_after:
                breaker.mark_as_broken(self.endpoint)
        except Exception:
            import logging
            logging.getLogger(__name__).exception(
                "supervisor crash report failed")

    # ---- the degradation ladder ----

    def _pressures(self) -> dict:
        q_us = 0.0
        if self.batcher is not None:
            try:
                q_us = float(
                    self.batcher.queue_delay_rec.latency_percentile(0.99))
            except Exception:
                q_us = 0.0
        pool = 0.0
        if self.store is not None:
            try:
                st = self.store.pagepool.stats()
                cap = st["max_blocks"] * st["pages_per_block"]
                pool = st["pages_in_use"] / cap if cap else 0.0
            except Exception:
                pool = 0.0
        depth = 0.0
        eng = self._engine
        if eng is not None:
            try:
                depth = eng.queue_depth()
            except Exception:
                depth = 0.0
        return {"queue_delay_us": q_us, "pool_ratio": pool,
                "queue_depth": depth}

    @property
    def level(self) -> int:
        """Current degradation level — the shared ladder's state."""
        return self._ladder.level

    def set_level_floor(self, floor: int) -> None:
        """Hold this replica at a minimum degradation level regardless
        of its local pressures — the cluster router's lever: when the
        CLUSTER gradient escalates past shed-at-router, every replica
        browns out / clamps / evicts together.  Applied on the next
        watchdog tick (or immediately by an explicit
        ``_update_degradation`` call)."""
        self._ladder.floor = max(0, min(int(floor), len(self.ladder)))

    def _target_level(self, p: dict) -> int:
        return self._ladder.target_level(p)

    def _update_degradation(self) -> None:
        # escalation immediate, de-escalation hysteretic — the policy
        # lives in the shared OverloadLadder (serving/ladder.py)
        self._ladder.update(self._pressures())
        self._apply_level()
        if self.state in ("healthy", "degraded"):
            self.state = "degraded" if self.level else "healthy"

    def _apply_level(self) -> None:
        lvl = self.level
        if self.batcher is not None:
            self.batcher.brownout = lvl
        eng = self._engine
        if eng is not None:
            eng.degraded_clamp = self.clamp_new_tokens if lvl >= 2 \
                else None
        if lvl >= 3 and self.store is not None:
            n = self.ladder_evict_pages
            if n is None:
                n = self.store.pagepool.pages_per_block
            freed = self.store.evict_pages(n)
            if freed:
                self.ladder_evictions.add(freed)

    # ---- lifecycle / introspection ----

    def join_idle(self, timeout_s: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._mu:
                if not self._live:
                    return True
            time.sleep(0.005)
        return False

    def close(self, timeout_s: float = 10.0) -> None:
        """Stop supervising and close the engine; pending requests
        complete with ELOGOFF (passed through — a closing supervisor
        does NOT re-admit).  The KV store stays up, caller-owned."""
        self._closing = True
        self._running = False
        self._wake.set()
        self._thread.join(timeout_s)
        # undo the ladder's side effects on CALLER-owned components: a
        # batcher that outlives its supervisor must not keep shedding
        # its lowest lane forever with nothing left to de-escalate it
        if self.batcher is not None:
            self.batcher.brownout = 0
        eng = self._engine
        with self._ecv:
            self._engine = None
            self._ecv.notify_all()
        if eng is not None:
            eng.close(timeout_s)
        # anything the engine close missed (e.g. mid-resubmission)
        err = errors.RpcError(errors.ELOGOFF, "supervisor closed")
        with self._mu:
            live = list(self._live.values())
        for sreq in live:
            self._finish(sreq, err)
        from brpc_tpu.bvar.variable import find_exposed
        for n in self._bvar_names:
            v = find_exposed(n)
            if v is not None:
                v.hide()

    def stats(self) -> dict:
        with self._mu:
            live = len(self._live)
        eng = self._engine
        quarantined = False
        if self.endpoint is not None:
            try:
                from brpc_tpu.policy.health_check import is_broken
                quarantined = is_broken(self.endpoint)
            except Exception:
                pass
        out = {
            "state": self.state,
            "degradation_level": self.level,
            "restarts": self.restarts_total.get_value(),
            "readmitted": self.readmitted.get_value(),
            "resumed_tokens": self.resumed_tokens.get_value(),
            "ladder_evictions": self.ladder_evictions.get_value(),
            "live_requests": live,
            "ladder": self._ladder.stats(),
            "engine": None if eng is None else eng.name,
            "heartbeat_deadline_s": self.heartbeat_deadline_s,
            "last_recovery": self.last_recovery,
            "quarantined": quarantined,
        }
        if self.endpoint is not None:
            out["endpoint"] = str(self.endpoint)
        return out
