"""The ``_telemetry`` service and the router-side ``FleetCollector`` —
the fleet telemetry plane (ISSUE 20).

Every observability layer this repo shipped (PR 5 rpcz, PR 6 hotspots,
PR 15 flight recorder) ends at its own process boundary: ``/vars`` and
``/rpcz`` answer for ONE process, while the cluster has been
multi-process since PR 16.  This module is the collection half that
turns per-process introspection into a fleet view:

  * :class:`TelemetryService` (``_telemetry``) registers in every
    serving process — replica, PS shard, trainer harness, router — and
    answers two INCREMENTAL pulls on one tensorframe RPC:

      ``Pull {cursor, max_spans, max_vars, filter}`` →
          a bounded snapshot of named bvars (Adder/PassiveStatus
          scalars, LatencyRecorder summaries, ``bvar/window.py``
          windowed series) + the PR 15 syscall-attribution counters
          (``write_syscalls``, bytes-per-write histogram, tls_batch
          hit/miss) + every FINISHED rpcz span whose collection seq is
          past ``cursor`` (:func:`brpc_tpu.rpcz.spans_since`).

      ``Trace {trace_id}`` → every collected span of ONE trace — the
          on-demand fan-out read behind the router's
          ``/rpcz?trace_id=`` cross-process tree.

    Payloads ride as inline JSON str fields on the tensorframe reply,
    the same packing discipline as the ``_cluster`` service's
    ``deployments`` field (1 MiB cap per field — the bounds above keep
    replies far under it).

  * :class:`FleetCollector` lives on the router: one ``Pull`` per tick
    per endpoint over the SAME short-timeout control channel the
    ``_cluster`` SetFloor push uses (piggybacking its transport — a
    dead replica costs control_timeout_ms, never the data plane's
    forward timeout), merged into fleet-wide time-series rings keyed
    ``(replica, model, metric)``.  Dead replicas are TOMBSTONED after
    consecutive pull failures — their series freeze and drop out of
    every cross-replica aggregate rather than silently averaging in —
    and the tombstone/recovery timeline is what the SLO engine's HOLD
    rule (``serving/slo.py``) reads to refuse canary decisions during a
    disruption.  Collector tick count and bytes-per-pull are published
    as bvars (the <2% overhead gate's measuring stick).

The rings are plain Python deques of ``(t, value)`` — NOT
LatencyRecorders: the native recorder pool has 512 slots per process
and a fleet of replicas × models × metrics would exhaust it.
"""
from __future__ import annotations

import json
import os
import time
from collections import deque
from typing import Callable, Optional

from brpc_tpu import errors, rpcz
from brpc_tpu.butil.lockprof import InstrumentedLock
from brpc_tpu.rpc.service import Service, method

TELEMETRY_SERVICE = "_telemetry"

# bounds on one Pull reply (each JSON field must stay far under the
# 1 MiB tensorframe str-field cap)
MAX_VARS = 2048
MAX_SPANS = 512


def telemetry_snapshot(max_vars: int = 512,
                       pattern: str = "*") -> dict:
    """The bounded bvar snapshot one ``Pull`` ships: scalar variables
    (Adders, PassiveStatus, gauges), LatencyRecorder summaries, and
    ``bvar/window.py`` windowed series values, each name counted once
    against ``max_vars`` (alphabetical, so truncation is deterministic);
    plus the PR 15 flight-recorder syscall attribution, which degrades
    to zeros when the native core is absent."""
    from brpc_tpu.butil import flight
    from brpc_tpu.bvar.recorder import LatencyRecorder
    from brpc_tpu.bvar.variable import exposed_variables
    from brpc_tpu.bvar.window import Window

    scalars: dict[str, float] = {}
    recorders: dict[str, dict] = {}
    windows: dict[str, dict] = {}
    truncated = False
    n = 0
    for name, var in sorted(exposed_variables(pattern).items()):
        if n >= max_vars:
            truncated = True
            break
        try:
            if isinstance(var, LatencyRecorder):
                c, s_us, m = var.snapshot()
                recorders[name] = {
                    "count": c,
                    "avg_us": round(var.latency(), 1),
                    "p50_us": round(var.latency_percentile(0.5), 1),
                    "p99_us": round(var.latency_percentile(0.99), 1),
                    "max_us": m,
                    "qps": round(var.qps(), 2),
                }
            elif isinstance(var, Window):
                windows[name] = {"value": var.get_value(),
                                 "window_s": var._window}
            else:
                v = var.get_value()
                if isinstance(v, bool):
                    v = int(v)
                if not isinstance(v, (int, float)):
                    continue   # non-numeric: not a series, not counted
                scalars[name] = v
        except Exception:
            continue   # one torn-down variable must not kill the pull
        n += 1
    return {
        "scalars": scalars,
        "recorders": recorders,
        "windows": windows,
        "syscalls": flight.syscall_counters(),
        "bytes_per_write": {k: v for k, v in
                            flight.write_size_hist().items() if v},
        "truncated": truncated,
    }


class TelemetryService(Service):
    """Per-process half of the fleet telemetry plane (see module
    docstring): incremental bvar/span pulls plus the on-demand
    one-trace read the router's rpcz fan-out issues."""

    NAME = TELEMETRY_SERVICE

    def __init__(self, *, name: str = ""):
        self.name = str(name)
        self.pulls = 0
        self.trace_queries = 0

    @method(request="tensorframe", response="tensorframe")
    def Pull(self, cntl, req):
        req = req or {}
        cursor = max(0, int(req.get("cursor", 0)))
        max_spans = min(MAX_SPANS, max(0, int(req.get("max_spans", 256))))
        max_vars = min(MAX_VARS, max(0, int(req.get("max_vars", 512))))
        pattern = str(req.get("filter") or "*")
        spans, hi = rpcz.spans_since(cursor, max_spans)
        self.pulls += 1
        return {
            "name": self.name,
            "pid": int(os.getpid()),
            "cursor": int(hi),
            "vars": json.dumps(telemetry_snapshot(max_vars, pattern),
                               separators=(",", ":")),
            "spans": json.dumps([rpcz.span_to_dict(s) for s in spans],
                                separators=(",", ":")),
        }

    @method(request="tensorframe", response="tensorframe")
    def Trace(self, cntl, req):
        req = req or {}
        try:
            tid = int(req.get("trace_id", 0))
        except (TypeError, ValueError):
            tid = 0
        if not tid:
            cntl.set_failed(errors.EREQUEST, 'missing "trace_id"')
            return None
        spans = rpcz.recent_spans(2048, tid)
        if not spans:
            spans = rpcz.load_disk_spans(2048, tid)
        self.trace_queries += 1
        return {
            "name": self.name,
            "pid": int(os.getpid()),
            "spans": json.dumps([rpcz.span_to_dict(s) for s in spans],
                                separators=(",", ":")),
        }

    def stats(self) -> dict:
        return {"pulls": self.pulls, "trace_queries": self.trace_queries}


def register_telemetry(server, *, name: str = "") -> TelemetryService:
    """Expose this process to the fleet telemetry plane (call before
    ``server.start()``)."""
    svc = TelemetryService(name=name)
    server.add_service(svc)
    return svc


def parse_spans_field(field) -> list:
    """Decode a ``spans`` reply field into Span objects, dropping any
    malformed record (one bad span from a remote process must not kill
    the merge)."""
    if not field:
        return []
    try:
        recs = json.loads(field)
    except (TypeError, ValueError):
        return []
    if not isinstance(recs, list):
        return []
    out = []
    for rec in recs:
        s = rpcz.span_from_dict(rec)
        if s is not None:
            out.append(s)
    return out


class FleetCollector:
    """Router-side aggregation (see module docstring): per-endpoint
    incremental pulls into fleet series rings, a bounded fleet span
    store for cross-process trace stitching, and the tombstone ledger
    the SLO engine's disruption HOLD reads."""

    RING = 128            # samples per (replica, model, metric) series
    SPAN_KEEP = 4096      # fleet span store bound
    TOMBSTONE_AFTER = 2   # consecutive failed pulls before tombstoning
    FANOUT_MAX_ADDRS = 16

    def __init__(self, name: str = "fleet", *,
                 control_timeout_ms: int = 1000,
                 var_filter: str = "*"):
        from brpc_tpu.bvar.reducer import Adder
        self.name = str(name)
        self.control_timeout_ms = int(control_timeout_ms)
        # glob over remote var names: a collector that only needs a
        # few series shouldn't make every replica snapshot (and both
        # sides JSON-codec) its whole namespace each pull
        self.var_filter = str(var_filter or "*")
        self._mu = InstrumentedLock("fleet.collector")
        # (replica, model, metric) -> deque[(t, value)]
        self._series: dict[tuple, deque] = {}
        # endpoint key -> replica state
        self._replicas: dict[str, dict] = {}
        # fleet span store: dedupe key -> Span, bounded FIFO
        self._spans: dict[tuple, object] = {}
        self._span_order: deque = deque()
        self._chan_by_addr: dict[str, object] = {}
        safe = self.name.replace(".", "_").replace("-", "_")
        self._bvar_names = [f"fleet_{safe}_pulls",
                            f"fleet_{safe}_pull_bytes",
                            f"fleet_{safe}_pull_errors",
                            f"fleet_{safe}_tombstones"]
        self.pulls = Adder(self._bvar_names[0])
        self.pull_bytes = Adder(self._bvar_names[1])
        self.pull_errors = Adder(self._bvar_names[2])
        self.tombstones = Adder(self._bvar_names[3])

    # ---- series rings -------------------------------------------------

    def _append(self, replica: str, model: str, metric: str,
                t: float, value: float) -> None:
        key = (str(replica), str(model), str(metric))
        ring = self._series.get(key)
        if ring is None:
            ring = self._series[key] = deque(maxlen=self.RING)
        ring.append((t, float(value)))

    def window_values(self, replica: str, model: str, metric: str,
                      window_s: float,
                      now: Optional[float] = None) -> list[float]:
        """Samples of one series within the trailing window."""
        now = time.monotonic() if now is None else now
        with self._mu:
            ring = self._series.get((str(replica), str(model),
                                     str(metric)))
            if not ring:
                return []
            return [v for (t, v) in ring if t >= now - window_s]

    def values_across(self, model: str, metric: str, window_s: float,
                      now: Optional[float] = None) -> list[float]:
        """Windowed samples of one (model, metric) across every
        NON-TOMBSTONED replica — the cross-replica aggregate the SLO
        engine evaluates.  A tombstoned replica's series is excluded
        entirely (frozen, never averaged) rather than padded."""
        now = time.monotonic() if now is None else now
        out: list[float] = []
        with self._mu:
            dead = {a for a, st in self._replicas.items()
                    if st.get("tombstoned")}
            for (rep, mod, met), ring in self._series.items():
                if mod != str(model) or met != str(metric):
                    continue
                if rep in dead:
                    continue
                out.extend(v for (t, v) in ring if t >= now - window_s)
        return out

    # ---- pulls --------------------------------------------------------

    def _state(self, addr: str) -> dict:
        st = self._replicas.get(addr)
        if st is None:
            st = self._replicas[addr] = {
                "addr": addr, "name": "", "pid": None, "cursor": 0,
                "pulls": 0, "errors": 0, "consec_errors": 0,
                "unsupported": False, "tombstoned": False,
                "tombstone_t": None, "recover_t": None,
                "last_pull_t": None, "last_bytes": 0}
        return st

    def pull(self, addr: str, channel, *, model_hint: str = "") -> bool:
        """One incremental ``Pull`` from ``addr`` over ``channel`` (the
        router's control channel — the SetFloor transport).  Returns
        True on success.  Failures count toward the tombstone; an
        ENOSERVICE/ENOMETHOD reply (process without the service)
        disables further pulls without tombstoning — absence of
        telemetry is not death."""
        with self._mu:
            st = self._state(addr)
            if st["unsupported"]:
                return False
            cursor = st["cursor"]
        try:
            resp = channel.call_sync(
                TELEMETRY_SERVICE, "Pull",
                {"cursor": int(cursor), "max_spans": 256,
                 "max_vars": MAX_VARS, "filter": self.var_filter},
                serializer="tensorframe",
                response_serializer="tensorframe")
        except errors.RpcError as e:
            with self._mu:
                st = self._state(addr)
                if e.code in (errors.ENOSERVICE, errors.ENOMETHOD):
                    st["unsupported"] = True
                    return False
                st["errors"] += 1
                st["consec_errors"] += 1
                self.pull_errors.add(1)
                if (not st["tombstoned"]
                        and st["consec_errors"] >= self.TOMBSTONE_AFTER):
                    st["tombstoned"] = True
                    st["tombstone_t"] = time.monotonic()
                    self.tombstones.add(1)
            return False
        now = time.monotonic()
        resp = resp or {}
        vars_field = resp.get("vars") or ""
        spans_field = resp.get("spans") or ""
        nbytes = len(vars_field) + len(spans_field)
        try:
            snap = json.loads(vars_field) if vars_field else {}
        except (TypeError, ValueError):
            snap = {}
        spans = parse_spans_field(spans_field)
        with self._mu:
            st = self._state(addr)
            if st["tombstoned"]:
                st["tombstoned"] = False
                st["recover_t"] = now
            st["consec_errors"] = 0
            st["pulls"] += 1
            st["cursor"] = max(st["cursor"],
                               int(resp.get("cursor", st["cursor"])))
            st["name"] = str(resp.get("name") or st["name"])
            st["pid"] = resp.get("pid", st["pid"])
            st["last_pull_t"] = now
            st["last_bytes"] = nbytes
            st["snapshot"] = snap
            # recorder p99/qps and windowed values become fleet series;
            # scalar counters stay in the last-snapshot table (/fleet)
            for nm, rec in (snap.get("recorders") or {}).items():
                try:
                    self._append(addr, model_hint, f"{nm}.p99_us",
                                 now, rec["p99_us"])
                    self._append(addr, model_hint, f"{nm}.qps",
                                 now, rec["qps"])
                except (KeyError, TypeError, ValueError):
                    continue
            for nm, win in (snap.get("windows") or {}).items():
                try:
                    self._append(addr, model_hint, nm, now,
                                 float(win["value"]))
                except (KeyError, TypeError, ValueError):
                    continue
            self._merge_spans_locked(spans)
        self.pulls.add(1)
        self.pull_bytes.add(nbytes)
        return True

    def note_dead(self, addr: str) -> None:
        """Tombstone ``addr`` immediately (the router already knows the
        replica is gone — no need to burn TOMBSTONE_AFTER pulls)."""
        with self._mu:
            st = self._state(addr)
            if not st["tombstoned"]:
                st["tombstoned"] = True
                st["tombstone_t"] = time.monotonic()
                self.tombstones.add(1)

    def sample_models(self, model_metrics, *,
                      replica: str = "router") -> None:
        """Sample the router-local per-(model, version) scoreboard
        (:class:`~brpc_tpu.serving.modelplane.ModelMetrics`) into fleet
        series — TTFT/ITL percentiles live on the ROUTER (it observes
        every stream), so these are the series the SLO engine burns
        against, keyed replica=\"router\"."""
        now = time.monotonic()
        snap = model_metrics.snapshot()
        with self._mu:
            for model, row in snap.items():
                ttft = (row.get("ttft") or {}).get("p99_ms")
                itl = (row.get("itl") or {}).get("p99_ms")
                if ttft is not None:
                    self._append(replica, model, "ttft_p99_ms", now, ttft)
                if itl is not None:
                    self._append(replica, model, "itl_p99_ms", now, itl)
                self._append(replica, model, "finished", now,
                             row.get("finished", 0))
                self._append(replica, model, "failed", now,
                             row.get("failed", 0))

    # ---- disruption window (the SLO HOLD input) -----------------------

    def tombstoned(self) -> list[str]:
        with self._mu:
            return sorted(a for a, st in self._replicas.items()
                          if st.get("tombstoned"))

    def disruption_within(self, window_s: float,
                          now: Optional[float] = None) -> bool:
        """True while any replica is tombstoned, or was tombstoned or
        recovered within the trailing window — the SLO engine HOLDs
        canary decisions inside this window (chaos-induced burn must
        not promote or roll back)."""
        now = time.monotonic() if now is None else now
        with self._mu:
            for st in self._replicas.values():
                if st.get("tombstoned"):
                    return True
                for k in ("tombstone_t", "recover_t"):
                    t = st.get(k)
                    if t is not None and now - t <= window_s:
                        return True
        return False

    # ---- fleet span store / trace stitching ---------------------------

    def _merge_spans_locked(self, spans) -> None:
        for s in spans:
            key = (s.trace_id, s.span_id, s.kind, s.start_us)
            if key in self._spans:
                continue
            self._spans[key] = s
            self._span_order.append(key)
            while len(self._span_order) > self.SPAN_KEEP:
                old = self._span_order.popleft()
                self._spans.pop(old, None)

    def merge_spans(self, spans) -> None:
        with self._mu:
            self._merge_spans_locked(spans)

    def fleet_spans(self, trace_id: int) -> list:
        with self._mu:
            return [s for s in self._spans.values()
                    if s.trace_id == trace_id]

    def _channel(self, addr: str):
        ch = self._chan_by_addr.get(addr)
        if ch is None:
            from brpc_tpu.rpc.channel import Channel
            ch = Channel(addr, timeout_ms=self.control_timeout_ms)
            self._chan_by_addr[addr] = ch
        return ch

    def fan_out_trace(self, trace_id: int,
                      addrs: Optional[list] = None) -> list:
        """The on-demand cross-process read behind ``/rpcz?trace_id=``
        on the router: merge (1) this process's collected/persisted
        spans, (2) the fleet span store, and (3) a live ``Trace`` query
        to every known endpoint PLUS every address discovered in
        already-merged client spans' ``remote_side`` — that second hop
        is how the PS shard the router never talks to directly joins
        the tree (the replica's client span names it).  Bounded to
        FANOUT_MAX_ADDRS queried addresses, each on a short-timeout
        control channel; a dead or telemetry-less process simply
        contributes nothing."""
        trace_id = int(trace_id)
        merged: dict[tuple, object] = {}

        def fold(spans) -> None:
            for s in spans:
                merged.setdefault(
                    (s.trace_id, s.span_id, s.kind, s.start_us), s)

        fold(rpcz.recent_spans(2048, trace_id))
        fold(rpcz.load_disk_spans(2048, trace_id))
        fold(self.fleet_spans(trace_id))
        with self._mu:
            known = [a for a, st in self._replicas.items()
                     if not st.get("unsupported")
                     and not st.get("tombstoned")]
        pending = list(addrs or ()) + known
        queried: set[str] = set()
        while pending and len(queried) < self.FANOUT_MAX_ADDRS:
            addr = str(pending.pop(0))
            if not addr or addr in queried:
                continue
            queried.add(addr)
            try:
                resp = self._channel(addr).call_sync(
                    TELEMETRY_SERVICE, "Trace",
                    {"trace_id": trace_id},
                    serializer="tensorframe",
                    response_serializer="tensorframe")
            except errors.RpcError:
                continue
            spans = parse_spans_field((resp or {}).get("spans"))
            fold(spans)
            # follow callee addresses the new spans name: the replica's
            # client span's remote_side is the PS shard's server
            for s in spans:
                peer = str(s.remote_side or "")
                if peer and peer not in queried:
                    pending.append(peer)
        out = list(merged.values())
        self.merge_spans(out)
        return out

    # ---- introspection ------------------------------------------------

    def series_snapshot(self, points: int = 32) -> dict:
        """Nested ``replica -> model -> metric -> [values...]`` view of
        the rings (last ``points`` samples) — the /fleet sparkline
        data."""
        out: dict = {}
        with self._mu:
            for (rep, mod, met), ring in sorted(self._series.items()):
                vals = [round(v, 4) for (_t, v) in list(ring)[-points:]]
                out.setdefault(rep, {}).setdefault(
                    mod or "-", {})[met] = vals
        return out

    def replica_table(self) -> list[dict]:
        now = time.monotonic()
        out = []
        with self._mu:
            for addr, st in sorted(self._replicas.items()):
                row = {k: st.get(k) for k in
                       ("addr", "name", "pid", "cursor", "pulls",
                        "errors", "consec_errors", "unsupported",
                        "tombstoned", "last_bytes")}
                row["pull_age_s"] = (
                    round(now - st["last_pull_t"], 3)
                    if st.get("last_pull_t") else None)
                syscalls = (st.get("snapshot") or {}).get("syscalls")
                if syscalls:
                    row["syscalls"] = syscalls
                out.append(row)
        return out

    def last_snapshot(self, addr: str) -> Optional[dict]:
        with self._mu:
            st = self._replicas.get(str(addr))
            return (st or {}).get("snapshot")

    def stats(self) -> dict:
        with self._mu:
            nseries = len(self._series)
            nspans = len(self._spans)
        return {
            "pulls": self.pulls.get_value(),
            "pull_bytes": self.pull_bytes.get_value(),
            "pull_errors": self.pull_errors.get_value(),
            "tombstones": self.tombstones.get_value(),
            "series": nseries,
            "fleet_spans": nspans,
            "replicas": self.replica_table(),
        }

    def close(self) -> None:
        from brpc_tpu.bvar.variable import find_exposed
        for n in self._bvar_names:
            v = find_exposed(n)
            if v is not None:
                v.hide()


__all__ = [
    "TELEMETRY_SERVICE", "TelemetryService", "register_telemetry",
    "telemetry_snapshot", "parse_spans_field", "FleetCollector",
]
