"""Command-line tools (reference tools/, SURVEY.md §2.8):

  python -m brpc_tpu.tools.rpc_press     — load generator
  python -m brpc_tpu.tools.rpc_replay    — replay rpc_dump captures
  python -m brpc_tpu.tools.rpc_view      — fetch a server's builtin pages
  python -m brpc_tpu.tools.parallel_http — mass concurrent HTTP fetcher
"""
