"""parallel_http — mass concurrent HTTP fetcher
(reference tools/parallel_http: fetch many URLs concurrently, report
success/failure counts and timing).

Example:
  python -m brpc_tpu.tools.parallel_http --url-file urls.txt --threads 32
"""
from __future__ import annotations

import argparse
import json
import sys
import threading
import time
import urllib.request
from queue import Empty, Queue

from brpc_tpu.bvar import LatencyRecorder


def fetch_all(urls: list[str], threads: int = 16, timeout: float = 5.0,
              out=sys.stderr) -> dict:
    q: Queue[str] = Queue()
    for u in urls:
        q.put(u)
    rec = LatencyRecorder("parallel_http")
    ok = [0]
    fail = [0]
    mu = threading.Lock()
    results: dict[str, int] = {}

    def worker():
        while True:
            try:
                u = q.get_nowait()
            except Empty:
                return
            t0 = time.monotonic()
            try:
                with urllib.request.urlopen(u, timeout=timeout) as r:
                    r.read()
                    status = r.status
                rec.add(int((time.monotonic() - t0) * 1e6))
                with mu:
                    ok[0] += 1
                    results[u] = status
            except Exception:
                with mu:
                    fail[0] += 1
                    results[u] = -1

    t_start = time.monotonic()
    ts = [threading.Thread(target=worker, daemon=True)
          for _ in range(min(threads, max(1, len(urls))))]
    [t.start() for t in ts]
    [t.join() for t in ts]
    summary = {
        "fetched": ok[0],
        "failed": fail[0],
        "p50_us": rec.latency_percentile(0.5),
        "p99_us": rec.latency_percentile(0.99),
        "elapsed_s": round(time.monotonic() - t_start, 2),
    }
    print(json.dumps(summary), file=out)
    summary["results"] = results
    return summary


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    g = ap.add_mutually_exclusive_group(required=True)
    g.add_argument("--url-file", help="file with one URL per line")
    g.add_argument("--url", action="append", help="URL (repeatable)")
    ap.add_argument("--threads", type=int, default=16)
    ap.add_argument("--timeout", type=float, default=5.0)
    a = ap.parse_args(argv)
    urls = a.url or []
    if a.url_file:
        with open(a.url_file) as f:
            urls.extend(line.strip() for line in f if line.strip())
    fetch_all(urls, threads=a.threads, timeout=a.timeout, out=sys.stdout)


if __name__ == "__main__":
    main()
